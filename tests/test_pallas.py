"""Pallas fused E+M kernel vs the jnp reference path (interpret mode on CPU).

SURVEY.md SS4: 'kernel tests: Pallas kernels in interpret=True mode vs the jnp
reference implementation'.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.mstep import accumulate_stats
from cuda_gmm_mpi_tpu.ops.pallas import should_use_pallas
from cuda_gmm_mpi_tpu.ops.pallas.fused_stats import fused_stats_pallas
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

from .conftest import make_blobs
from .test_estep import make_state

pallas_interp = functools.partial(fused_stats_pallas, block_b=64,
                                  interpret=True)


def to_f32(state):
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32) if a.dtype != bool else a, state
    )


def test_fused_stats_matches_jnp(rng):
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    out = pallas_interp(state, chunks, wts)

    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_fused_stats_masking(rng):
    """Padded events and inactive clusters contribute exactly nothing."""
    k, d, n, b = 4, 3, 128, 64
    state = to_f32(make_state(rng, k, d, inactive=(2,)))
    data = rng.normal(size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts_np = np.ones((n // b, b), np.float32)
    wts_np[-1, 32:] = 0.0  # pad out the tail
    out = pallas_interp(state, chunks, jnp.asarray(wts_np))
    ref = accumulate_stats(state, chunks, jnp.asarray(wts_np),
                           matmul_precision="highest")
    assert float(out.Nk[2]) == 0.0
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)


def test_fused_stats_uneven_tiles(rng):
    """Event count not divisible by block_b: internal padding handles it."""
    k, d = 3, 3
    state = to_f32(make_state(rng, k, d))
    data = rng.normal(size=(96, d)).astype(np.float32)  # 96 = 1.5 * 64
    chunks = jnp.asarray(data.reshape(2, 48, d))
    wts = jnp.ones((2, 48), jnp.float32)
    out = pallas_interp(state, chunks, wts)
    ref = accumulate_stats(state, chunks, wts, matmul_precision="highest")
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_backend(rng):
    """Full EM through GMMModel with the kernel as stats backend."""
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32")
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=pallas_interp)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_fused_stats_diag_matches_jnp(rng):
    """DIAG_ONLY mode (gaussian_kernel.cu:215-223,430-433,621-628)."""
    k, d, n, b = 5, 4, 256, 64
    state = to_f32(make_state(rng, k, d))  # both paths read only diag(Rinv)
    data = rng.normal(scale=2.0, size=(n, d)).astype(np.float32)
    chunks = jnp.asarray(data.reshape(n // b, b, d))
    wts = jnp.ones((n // b, b), jnp.float32)

    ref = accumulate_stats(state, chunks, wts, diag_only=True,
                           matmul_precision="highest")
    out = pallas_interp(state, chunks, wts, diag_only=True)

    assert out.M2.shape == (k, d)  # diagonal stats, like the jnp path
    np.testing.assert_allclose(float(out.loglik), float(ref.loglik), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out.Nk), np.asarray(ref.Nk),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.M1), np.asarray(ref.M1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.M2), np.asarray(ref.M2),
                               rtol=1e-4, atol=1e-3)


def test_em_loop_with_pallas_diag_backend(rng):
    data, _ = make_blobs(rng, n=512, d=3, k=3, dtype=np.float32)
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128, dtype="float32",
                    diag_only=True)
    m_ref = GMMModel(cfg)
    m_pal = GMMModel(cfg, stats_fn=functools.partial(pallas_interp,
                                                     diag_only=True))
    chunks, wts = chunk_events(data, cfg.chunk_size)
    chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
    state = seed_clusters_host(data, 3)
    eps = convergence_epsilon(*data.shape)
    s_ref, ll_ref, _ = m_ref.run_em(state, chunks, wts, eps)
    s_pal, ll_pal, _ = m_pal.run_em(state, chunks, wts, eps)
    np.testing.assert_allclose(float(ll_pal), float(ll_ref), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s_pal.means), np.asarray(s_ref.means),
                               rtol=1e-3, atol=1e-3)


def test_should_use_pallas_gating():
    assert not should_use_pallas(GMMConfig(use_pallas="never"))
    assert should_use_pallas(GMMConfig(use_pallas="always", diag_only=True))
    assert not should_use_pallas(GMMConfig(use_pallas="always",
                                           dtype="float64"))
    assert should_use_pallas(GMMConfig(use_pallas="always"))
    assert not should_use_pallas(GMMConfig(use_pallas="always"),
                                 cluster_sharded=True)
    # auto on CPU -> False
    assert not should_use_pallas(GMMConfig(use_pallas="auto"))
