"""Closed-loop model lifecycle (rev v2.6; docs/ROBUSTNESS.md "Model
lifecycle"): drift-triggered shadow retrain, canary gates, guarded
promotion, and automatic rollback.

Covers the PR's contracts:
- registry staging: ``stage: candidate`` versions are invisible to
  enumeration / ``latest_fingerprint`` / the poll / default load /
  ``maybe_reload`` until promoted; promotion is atomic (manifest flip
  first, marker removal last) and a torn promotion stays invisible AND
  retryable; quarantine pins a reason file; rollback re-publishes the
  pinned prior version bit-identically;
- the full in-process arc: debounced drift alarms -> shadow
  minibatch-EM retrain from spooled request rows -> canary gates +
  duplicate-dispatch shadow window -> promote via the EXISTING
  hot-reload swap -> watch probation -> cooldown;
- the chaos matrix: ``retrain_fail`` drives the jittered-backoff retry
  ladder into an attempt quarantine with the serving path untouched;
  ``canary_regression`` rejects the candidate with BYTE-identical
  client responses; ``promote_torn`` leaves the candidate invisible
  and the flip retryable; a post-promotion violation auto-rolls back
  with bit-identical scoring vs the pre-promotion server;
- lifecycle is OFF by default: an unbound server's responses and
  stream shape are untouched, and a bound-but-idle controller adds no
  events and changes no reply bytes;
- policy parsing rejects unknown knobs loudly; ``gmm serve
  --lifecycle`` requires the drift plane; the standalone ``gmm
  lifecycle`` CLI honours the 0/1/2 exit contract;
- every transition is a schema-valid ``lifecycle`` event consumed by
  ``gmm report`` / ``gmm top`` and gated by ``gmm diff`` defaults
  (``lifecycle.rollbacks>0`` / ``lifecycle.quarantines>0``).
"""

import json
import os

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, GaussianMixture, telemetry
from cuda_gmm_mpi_tpu.lifecycle import (LifecycleController,
                                        LifecycleError, LifecyclePolicy)
from cuda_gmm_mpi_tpu.serving import (GMMServer, ModelRegistry,
                                      RegistryError)
from cuda_gmm_mpi_tpu.telemetry.schema import validate_stream
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import make_blobs

STATE_LEAVES = ("means", "pi", "R", "Rinv", "N", "active", "avgvar",
                "constant")


@pytest.fixture(scope="module")
def fitted_world(tmp_path_factory):
    """One fitted mixture + its training data, shared read-only by the
    module (every test gets its OWN registry copy via ``world``)."""
    gen = np.random.default_rng(7)
    data, _ = make_blobs(gen, n=600, d=4, k=3, dtype=np.float64)
    data = data.astype(np.float32)
    gm = GaussianMixture(
        3, target_components=3,
        config=GMMConfig(min_iters=4, max_iters=4, chunk_size=256,
                         dtype="float32"))
    gm.fit(data)
    return gm, data


def world(fitted_world, tmp_path, **policy_overrides):
    """Fresh registry + controller + drift-enabled server."""
    gm, data = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    reg = ModelRegistry(root)
    spec = {
        "debounce_alarms": 1,
        "cooldown_s": 600.0,
        "holdout_rows": 128,
        "retrain": {"steps": 3, "min_rows": 64, "chunk_size": 256,
                    "backoff_base_s": 0.0, "backoff_max_s": 0.0},
        # A drift-adapting candidate legitimately scores a drifted
        # holdout very differently; tests gate on the regression arm.
        "canary": {"max_psi": 100.0, "max_ks": 1.0, "shadow_ticks": 2},
        "watch": {"probation_ticks": 2, "probation_s": 0.0,
                  "min_rows": 10},
    }
    for key, val in policy_overrides.items():
        if isinstance(val, dict):
            spec.setdefault(key, {}).update(val)
        else:
            spec[key] = val
    ctl = LifecycleController(reg, LifecyclePolicy(spec))
    server = GMMServer(reg, warm=False, drift_interval_s=3600.0,
                       drift_psi_threshold=0.2, lifecycle=ctl)
    return reg, ctl, server


def traffic(server, data, shift=0.0, requests=12, rows=40, start=0):
    """Replies (latency scrubbed -- wall clock is not payload)."""
    outs = []
    for i in range(requests):
        lo = ((start + i) * 17) % (len(data) - rows)
        x = (data[lo:lo + rows] + np.float32(shift)).tolist()
        resp = server.handle_requests(
            [{"id": i, "model": "m", "op": "score_samples", "x": x}])[0]
        assert resp["ok"], resp
        outs.append(json.dumps({k: v for k, v in resp.items()
                                if k != "latency_ms"}, sort_keys=True))
    return outs


class _Sink:
    def __init__(self, records):
        self._records = records

    def write(self, line):
        self._records.append(json.loads(line))

    def flush(self):
        pass


def lifecycle_events(stream):
    return [r for r in stream if r["event"] == "lifecycle"]


# ------------------------------------------------------------------ policy


def test_policy_defaults_and_unknown_knob_rejection(tmp_path):
    """A typo in a promotion policy is a silent outage -- the parser
    must reject unknown knobs at EVERY level, loudly, naming the valid
    set; valid specs merge over documented defaults."""
    p = LifecyclePolicy()
    assert p.debounce_alarms == 2 and p.cooldown_s == 300.0
    assert p.retrain["retries"] == 3 and p.canary["shadow_ticks"] == 3
    assert p.watch["probation_ticks"] == 20 and p.models == []

    p = LifecyclePolicy({"models": ["m"], "debounce_alarms": 1,
                         "retrain": {"steps": 5}})
    assert p.models == ["m"] and p.retrain["steps"] == 5
    assert p.retrain["retries"] == 3  # sibling defaults survive

    with pytest.raises(LifecycleError, match="unknown lifecycle policy"):
        LifecyclePolicy({"debounce": 1})
    with pytest.raises(LifecycleError, match="retrain.'setps'"):
        LifecyclePolicy({"retrain": {"setps": 5}})
    with pytest.raises(LifecycleError, match="must be an object"):
        LifecyclePolicy({"canary": 3})
    with pytest.raises(LifecycleError, match="min_rows"):
        LifecyclePolicy({"retrain": {"min_rows": 0}})

    pol = tmp_path / "p.json"
    pol.write_text(json.dumps({"cooldown_s": 60}))
    assert LifecyclePolicy.from_file(str(pol)).cooldown_s == 60.0
    pol.write_text("[1, 2]")
    with pytest.raises(LifecycleError, match="JSON object"):
        LifecyclePolicy.from_file(str(pol))
    with pytest.raises(LifecycleError, match="cannot read"):
        LifecyclePolicy.from_file(str(tmp_path / "ghost.json"))


def test_serve_cli_lifecycle_flag_requires_drift_plane(fitted_world,
                                                       tmp_path):
    """``--lifecycle`` without ``--drift-interval-s`` (and a broken
    policy file) are usage errors at startup, never a silently inert
    loop."""
    from cuda_gmm_mpi_tpu.serving.server import serve_main

    gm, _ = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    pol = tmp_path / "p.json"
    pol.write_text(json.dumps({"debounce_alarms": 1}))

    with pytest.raises(SystemExit) as e:
        serve_main(["--registry", root, "--lifecycle", str(pol)])
    assert e.value.code == 2
    pol.write_text(json.dumps({"nope": 1}))
    with pytest.raises(SystemExit) as e:
        serve_main(["--registry", root, "--lifecycle", str(pol),
                    "--drift-interval-s", "3600"])
    assert e.value.code == 2


# ------------------------------------------------------- registry staging


def test_candidate_stage_invisible_until_promoted(fitted_world, tmp_path):
    """The staging contract every other guarantee rests on: a
    ``stage: candidate`` version does not exist for enumeration, the
    fingerprint poll, default load, or ``maybe_reload`` -- only an
    explicit version pin (the canary scorer) sees it. Promotion flips
    it live atomically; a quarantined version refuses promotion."""
    gm, data = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    reg = ModelRegistry(root)
    server = GMMServer(reg, warm=False)
    before = traffic(server, data, requests=2)

    fp1 = reg.latest_fingerprint("m")
    vc = reg.save("m", gm.result_, covariance_type="full",
                  source="lifecycle", stage="candidate")
    assert vc == 2
    assert reg.versions("m") == [1]
    assert reg.versions("m", include_candidates=True) == [1, 2]
    assert reg.models() == ["m"]
    assert reg.latest_fingerprint("m") == fp1
    assert reg.poll({"m": fp1}) == {}
    assert reg.load("m").version == 1          # default load skips it
    assert reg.load("m", 2).version == 2       # explicit pin sees it
    assert reg.stage("m", 2) == "candidate"
    assert server.maybe_reload() == []         # hot reload skips it
    assert traffic(server, data, requests=2) == before

    reg.promote("m", 2)
    assert reg.stage("m", 2) == "live"
    assert reg.versions("m") == [1, 2]
    swaps = server.maybe_reload()              # NOW the swap happens
    assert [s["to_version"] for s in swaps] == [2]

    reg.quarantine("m", 2, {"reason": "test"})
    assert reg.stage("m", 2) == "quarantined"
    assert reg.versions("m") == [1]
    with pytest.raises(RegistryError, match="quarantined"):
        reg.promote("m", 2)
    qdoc = json.loads(
        open(os.path.join(root, "m", "2", "quarantine.json")).read())
    assert qdoc["reason"] == "test" and qdoc["version"] == 2


def test_torn_promotion_stays_invisible_and_retryable(fitted_world,
                                                      tmp_path):
    """The ``promote_torn`` fault point sits between the manifest flip
    and the marker removal: a crash there leaves the version invisible
    (marker is authoritative) and a RETRY of the same promotion
    completes it."""
    gm, _ = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    reg = ModelRegistry(root)
    reg.save("m", gm.result_, covariance_type="full", stage="candidate")
    with faults.use({"promote_torn": {"name": "m", "times": 1}}) as f:
        with pytest.raises(RegistryError, match="promote_torn"):
            reg.promote("m", 2)
        assert f.fired.get("promote_torn") == 1
    assert reg.versions("m") == [1]            # still invisible
    assert reg.stage("m", 2) == "candidate"
    reg.promote("m", 2)                        # the retry wins
    assert reg.versions("m") == [1, 2]


def test_rollback_republishes_prior_version_bit_identical(fitted_world,
                                                          tmp_path):
    """Rollback re-publishes the pinned prior version as the NEWEST
    live version with every npz leaf bit-equal, and quarantines the bad
    promotion with the reason + restored-as breadcrumbs."""
    gm, _ = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    gm.to_registry(root, "m")
    reg = ModelRegistry(root)
    new_v = reg.rollback("m", to_version=1, bad_version=2,
                         reason={"reason": "score_regression"})
    assert new_v == 3
    assert reg.versions("m") == [1, 3]
    assert reg.stage("m", 2) == "quarantined"
    qdoc = json.loads(
        open(os.path.join(root, "m", "2", "quarantine.json")).read())
    assert qdoc["reason"] == "score_regression"
    assert qdoc["restored_version"] == 1 and qdoc["restored_as"] == 3
    m1, m3 = reg.load("m", 1), reg.load("m", 3)
    for leaf in STATE_LEAVES:
        assert np.array_equal(np.asarray(getattr(m1.state, leaf)),
                              np.asarray(getattr(m3.state, leaf))), leaf
    assert np.array_equal(np.asarray(m1.data_shift),
                          np.asarray(m3.data_shift))
    assert m3.manifest["source"] == "rollback"
    assert m3.manifest["restored_version"] == 1
    assert m3.manifest["rollback_of"] == 2


# ------------------------------------------------------------- the arc


def test_full_arc_drift_retrain_canary_promote_watch(fitted_world,
                                                     tmp_path):
    """The happy path end to end, in process: shifted traffic raises
    the alarm, the next ticks run retrain (from the request spool) ->
    canary (holdout gates + 2-tick duplicate-dispatch shadow window) ->
    promote (the existing hot-reload swap) -> watch -> cooldown ->
    idle. Every transition is a schema-valid ``lifecycle`` event."""
    reg, ctl, server = world(fitted_world, tmp_path,
                             cooldown_s=0.0)
    gm, data = fitted_world
    stream = []
    rec = telemetry.RunRecorder(stream=_Sink(stream))
    with telemetry.use(rec), rec:
        assert server.resolve("m").version == 1
        traffic(server, data, shift=8.0)
        out = server.flush_drift()
        assert out and out[0]["alarm"]
        assert ctl.stats()["routes"]["m"] == "retrain"
        ctl.on_tick()                          # refit + holdout gates
        assert ctl.stats()["routes"]["m"] == "canary"
        assert reg.versions("m") == [1]        # candidate invisible
        assert server.resolve("m").version == 1
        traffic(server, data, shift=8.0, requests=2, start=50)  # shadow
        ctl.on_tick()                          # close canary -> promote
        st = ctl.stats()
        assert st["promotes"] == 1 and st["routes"]["m"] == "watch"
        assert server.resolve("m").version == 2
        assert reg.versions("m") == [1, 2]
        traffic(server, data, shift=8.0, requests=3, start=80)
        ctl.on_tick()                          # probation closes clean
        assert ctl.stats()["routes"]["m"] == "cooldown"
        ctl.on_tick()                          # cooldown_s=0 -> idle
        assert ctl.stats()["routes"]["m"] == "idle"

    assert validate_stream(stream) == []
    arcs = [(e["phase"], e.get("outcome")) for e in
            lifecycle_events(stream)]
    assert arcs == [("retrain", "scheduled"), ("retrain", "published"),
                    ("canary", "pass"), ("promote", "promoted"),
                    ("watch", "passed")]
    canary = [e for e in lifecycle_events(stream)
              if e["phase"] == "canary"][0]
    for field in ("psi", "ks", "mean_incumbent", "mean_candidate",
                  "regression", "tolerance", "shadow_rows",
                  "shadow_ticks"):
        assert field in canary, field
    assert canary["shadow_ticks"] == 2
    assert ctl.counts == {"retrains": 1, "canaries": 1, "promotes": 1,
                          "rollbacks": 0, "quarantines": 0}
    man = reg.load("m", 2).manifest
    assert man["source"] == "lifecycle" and man["retrain_of"] == 1


def test_post_promotion_violation_rolls_back_bit_identical(fitted_world,
                                                           tmp_path):
    """The acceptance chaos case: an injected post-promotion score
    regression (traffic from a far-worse distribution during probation)
    auto-rolls back to the pinned prior version; afterwards a fixed
    probe scores BIT-identically to the pre-promotion server and the
    bad candidate is quarantined with a reason file."""
    reg, ctl, server = world(fitted_world, tmp_path,
                             watch={"probation_ticks": 64,
                                    "probation_s": 600.0,
                                    "min_rows": 10})
    gm, data = fitted_world
    stream = []
    rec = telemetry.RunRecorder(stream=_Sink(stream))
    with telemetry.use(rec), rec:
        probe_before = traffic(server, data, requests=1, start=7)
        traffic(server, data, shift=8.0)
        server.flush_drift()
        ctl.on_tick()                                   # -> canary
        traffic(server, data, shift=8.0, requests=2, start=50)
        ctl.on_tick()                                   # -> watch (v2)
        assert server.resolve("m").version == 2
        traffic(server, data, shift=40.0, requests=3, start=100)
        ctl.on_tick()                                   # -> rollback
        st = ctl.stats()
        assert st["rollbacks"] == 1 and st["quarantines"] == 1
        assert st["routes"]["m"] == "cooldown"
        # v2 quarantined; v1 re-published as v3 and SERVED
        assert reg.versions("m") == [1, 3]
        assert reg.stage("m", 2) == "quarantined"
        assert server.resolve("m").version == 3
        probe_after = traffic(server, data, requests=1, start=7)

    # scoring after rollback is bit-identical to before the promotion
    # (the npz round-trip restores the exact leaves) -- only the served
    # version number moved
    b = json.loads(probe_before[0])
    a = json.loads(probe_after[0])
    assert b.pop("version") == 1 and a.pop("version") == 3
    assert a == b
    assert validate_stream(stream) == []
    ev = lifecycle_events(stream)
    assert [(e["phase"], e.get("outcome")) for e in ev][-3:] == [
        ("watch", "violated"), ("rollback", None), ("quarantine", None)]
    rb = ev[-2]
    assert rb["from_version"] == 2 and rb["to_version"] == 3
    assert rb["reason"] == "score_regression"


# ----------------------------------------------------------- chaos matrix


def test_retrain_fail_fault_retries_then_quarantines(fitted_world,
                                                     tmp_path):
    """``retrain_fail`` drives the checkpoint-retries recipe: one retry
    event per failed attempt, then exhaustion quarantines the ATTEMPT
    (no artifact exists) and opens a cooldown -- with the serving path
    never touched."""
    reg, ctl, server = world(fitted_world, tmp_path)
    gm, data = fitted_world
    stream = []
    rec = telemetry.RunRecorder(stream=_Sink(stream))
    with telemetry.use(rec), rec, \
            faults.use({"retrain_fail": {"model": "m", "times": 99}}):
        before = traffic(server, data, shift=8.0)
        server.flush_drift()
        for _ in range(10):
            ctl.on_tick()
        st = ctl.stats()
        assert st["retrains"] == 0 and st["quarantines"] == 1
        assert st["routes"]["m"] == "cooldown"
        assert reg.versions("m", include_candidates=True) == [1]
        assert server.resolve("m").version == 1
        after = traffic(server, data, shift=8.0)
    assert after == before                     # byte-identical replies
    assert validate_stream(stream) == []
    ev = lifecycle_events(stream)
    retries = [e for e in ev if e.get("outcome") == "retry"]
    assert len(retries) == 3                   # retries=3 -> 3 retry edges
    assert all("retrain_fail" in e["reason"] for e in retries)
    assert all("retry_in_s" in e for e in retries)
    q = [e for e in ev if e["phase"] == "quarantine"]
    assert len(q) == 1 and "retrain_exhausted" in q[0]["reason"]


def test_canary_regression_fault_quarantines_byte_identical(fitted_world,
                                                            tmp_path):
    """``canary_regression`` poisons only the SHADOW score: the gate
    rejects, the candidate is quarantined on disk, and the A/B replay
    proves zero client-visible change -- byte-identical responses
    before and after the failed canary."""
    reg, ctl, server = world(fitted_world, tmp_path)
    gm, data = fitted_world
    stream = []
    rec = telemetry.RunRecorder(stream=_Sink(stream))
    with telemetry.use(rec), rec:
        a_before = traffic(server, data, shift=8.0)
        with faults.use({"canary_regression": {"model": "m",
                                               "times": 1}}) as f:
            server.flush_drift()
            ctl.on_tick()
            assert f.fired.get("canary_regression") == 1
        st = ctl.stats()
        assert st["quarantines"] == 1 and st["routes"]["m"] == "cooldown"
        assert reg.versions("m") == [1]
        assert reg.stage("m", 2) == "quarantined"
        a_after = traffic(server, data, shift=8.0)
    assert a_after == a_before
    rej = [e for e in lifecycle_events(stream)
           if e.get("outcome") == "rejected"]
    assert len(rej) == 1 and rej[0]["phase"] == "canary"
    assert rej[0]["regression"] > rej[0]["tolerance"]


def test_promote_torn_fault_controller_retries_next_tick(fitted_world,
                                                         tmp_path):
    """A torn promotion mid-arc: the controller emits the torn edge,
    the candidate stays invisible to serving, and the NEXT tick retries
    the same promotion to completion."""
    reg, ctl, server = world(fitted_world, tmp_path,
                             canary={"shadow_ticks": 1})
    gm, data = fitted_world
    stream = []
    rec = telemetry.RunRecorder(stream=_Sink(stream))
    with telemetry.use(rec), rec:
        traffic(server, data, shift=8.0)
        with faults.use({"promote_torn": {"name": "m", "times": 1}}):
            server.flush_drift()
            ctl.on_tick()                      # retrain -> canary
            traffic(server, data, shift=8.0, requests=1, start=50)
            ctl.on_tick()                      # promote: TORN
        st = ctl.stats()
        assert st["promotes"] == 0 and st["routes"]["m"] == "canary"
        assert reg.versions("m") == [1]
        assert server.resolve("m").version == 1
        ctl.on_tick()                          # the retry completes
        st = ctl.stats()
        assert st["promotes"] == 1 and st["routes"]["m"] == "watch"
        assert server.resolve("m").version == 2
    ev = lifecycle_events(stream)
    torn = [e for e in ev if e.get("outcome") == "torn"]
    assert len(torn) == 1 and torn[0]["attempt"] == 1
    promoted = [e for e in ev if e.get("outcome") == "promoted"]
    assert promoted and promoted[0]["attempt"] == 2


# ------------------------------------------------------------ off-by-default


def test_lifecycle_off_by_default_byte_identical(fitted_world, tmp_path):
    """Without ``--lifecycle`` nothing changes (the PR-17 contract);
    and a BOUND but never-triggered controller adds zero events and
    zero reply-byte changes vs an unbound server on identical
    in-distribution traffic."""
    gm, data = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")
    reg = ModelRegistry(root)

    def run(lifecycle):
        server = GMMServer(reg, warm=False, drift_interval_s=3600.0,
                           drift_psi_threshold=0.2, lifecycle=lifecycle)
        stream = []
        rec = telemetry.RunRecorder(stream=_Sink(stream))
        with telemetry.use(rec), rec:
            replies = traffic(server, data)    # in-distribution: quiet
            server.flush_drift()
        return replies, stream

    plain_replies, plain_stream = run(None)
    ctl = LifecycleController(
        reg, LifecyclePolicy({"debounce_alarms": 1}))
    bound_replies, bound_stream = run(ctl)

    assert bound_replies == plain_replies
    assert [r["event"] for r in bound_stream] \
        == [r["event"] for r in plain_stream]
    assert lifecycle_events(bound_stream) == []
    assert ctl.stats()["routes"] == {"m": "idle"}
    assert ctl.counts["retrains"] == 0


# ------------------------------------------------------------ offline CLI


def test_gmm_lifecycle_cli_offline_promotes_and_exit_codes(fitted_world,
                                                           tmp_path,
                                                           capsys):
    """The standalone loop over a RECORDED stream: debounced alarms
    drive retrain -> canary -> promote (no shadow window offline; the
    next serve run adopts the result), exit 0; a quarantining run exits
    1; unknown policy knobs exit 2."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    gm, data = fitted_world
    root = str(tmp_path / "reg")
    gm.to_registry(root, "m")

    stream_path = tmp_path / "serve.jsonl"
    with open(stream_path, "w") as f:
        for t in (1.0, 2.0):
            f.write(json.dumps({"event": "drift_alarm", "t": t,
                                "model": "m", "version": 1,
                                "psi": 9.9, "threshold": 0.2}) + "\n")
        f.write('{"torn tail')                 # live streams end torn

    shifted = data + np.float32(8.0)
    bin_path = tmp_path / "shift.bin"
    with open(bin_path, "wb") as f:
        np.asarray(shifted.shape, np.int32).tofile(f)
        shifted.astype(np.float32).tofile(f)

    pol = tmp_path / "policy.json"
    pol.write_text(json.dumps({
        "debounce_alarms": 2, "cooldown_s": 1.0,
        "retrain": {"steps": 3, "min_rows": 64},
        "canary": {"max_psi": 100.0, "max_ks": 1.0}}))

    out_path = tmp_path / "lc.jsonl"
    rc = cli_main(["lifecycle", str(stream_path), "--registry", root,
                   "--policy", str(pol), "--data", str(bin_path),
                   "--out", str(out_path), "--json"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out.strip())
    assert verdict["alarms"] == 2
    assert verdict["counts"]["promotes"] == 1
    assert verdict["routes"]["m"]["live_versions"] == [1, 2]
    assert ModelRegistry(root).versions("m") == [1, 2]
    kinds = [json.loads(line)["event"]
             for line in open(out_path) if line.strip()]
    assert "lifecycle" in kinds

    # quarantine path (injected retrain failures) -> exit 1
    with faults.use({"retrain_fail": {"model": "m", "times": 99}}):
        rc = cli_main(["lifecycle", str(stream_path), "--registry", root,
                       "--policy", str(pol), "--data", str(bin_path)])
    assert rc == 1
    assert "quarantine" in capsys.readouterr().out

    # unknown knob -> usage error 2
    pol.write_text(json.dumps({"debounce": 1}))
    rc = cli_main(["lifecycle", str(stream_path), "--registry", root,
                   "--policy", str(pol)])
    assert rc == 2
    assert "unknown lifecycle policy" in capsys.readouterr().err


# ------------------------------------------------- observability surfaces


def test_report_top_and_diff_consume_lifecycle_events(fitted_world,
                                                      tmp_path, capsys):
    """The rendering/gating surfaces: ``gmm report`` renders the
    lifecycle section and the torn-registry line, ``gmm top`` shows the
    rollup, ``summarize_run`` folds the counts, and the DEFAULT ``gmm
    diff`` gates trip on rollbacks/quarantines."""
    from cuda_gmm_mpi_tpu.cli import main as cli_main
    from cuda_gmm_mpi_tpu.telemetry import timeline as tl_timeline
    from cuda_gmm_mpi_tpu.telemetry.diff import (DEFAULT_FAIL_ON,
                                                 summarize_run)

    assert "lifecycle.rollbacks>0" in DEFAULT_FAIL_ON
    assert "lifecycle.quarantines>0" in DEFAULT_FAIL_ON
    assert "lifecycle" in tl_timeline._THREAD_INSTANTS
    assert "registry_torn" in tl_timeline._THREAD_INSTANTS

    def synthesize(with_lifecycle):
        """A minimal serve-shaped stream with the REAL envelope (the
        recorder stamps schema/ts/run_id/process) so validate_stream
        and the diff fingerprint logic see production records."""
        records = []
        rec = telemetry.RunRecorder(stream=_Sink(records))
        with telemetry.use(rec), rec:
            rec.emit("run_start", platform="cpu", num_events=960,
                     num_dimensions=4, start_k=3, epsilon=1e-4)
            if with_lifecycle:
                rec.emit("lifecycle", model="m", phase="retrain",
                         outcome="published", candidate_version=2)
                rec.emit("lifecycle", model="m", phase="canary",
                         outcome="pass", psi=0.01, ks=0.02,
                         regression=-1.5, tolerance=2.0)
                rec.emit("lifecycle", model="m", phase="promote",
                         outcome="promoted", from_version=1,
                         to_version=2)
                rec.emit("lifecycle", model="m", phase="watch",
                         outcome="violated", reason="score_regression")
                rec.emit("lifecycle", model="m", phase="rollback",
                         from_version=2, to_version=3, version=1,
                         reason="score_regression")
                rec.emit("lifecycle", model="m", phase="quarantine",
                         version=2, reason="score_regression")
                rec.emit("registry_torn", model="m", version=9,
                         error="RegistryError: torn")
            rec.emit("serve_summary", requests=24, batches=24, rows=960,
                     wall_s=9.0, qps=2.7, latency_ms={"p50": 1.0},
                     metrics={}, errors=0)
        return records

    good, bad = synthesize(False), synthesize(True)
    assert validate_stream(bad) == []
    a_path, b_path = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, records in ((a_path, good), (b_path, bad)):
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")

    m = summarize_run(bad)["metrics"]
    assert m["lifecycle.retrains"] == 1 and m["lifecycle.promotes"] == 1
    assert m["lifecycle.rollbacks"] == 1
    assert m["lifecycle.quarantines"] == 1
    assert m["registry.torn"] == 1
    # the baseline's serve run pins explicit zeros for the count gates
    assert summarize_run(good)["metrics"]["lifecycle.rollbacks"] == 0.0

    assert cli_main(["report", b_path]) == 0
    out = capsys.readouterr().out
    assert "Lifecycle" in out
    assert "promote" in out and "rollback" in out
    assert "registry torn" in out
    assert cli_main(["top", b_path, "--interval", "0.01"]) == 0
    out = capsys.readouterr().out
    assert "lifecycle" in out

    # default diff gates: rollback + quarantine each name a regression
    assert cli_main(["diff", a_path, a_path]) == 0
    capsys.readouterr()
    assert cli_main(["diff", a_path, b_path]) == 1
    out = capsys.readouterr().out
    assert "lifecycle.rollbacks" in out
    assert "lifecycle.quarantines" in out
