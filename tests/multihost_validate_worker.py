"""Worker for the 2-process collective-abort test (input validation).

Rank 1's slice of the dataset contains a NaN row; rank 0's is clean. The
validator must bring BOTH ranks to the same InvalidInputError (via the
allgather_host agreement) instead of rank 1 aborting alone and rank 0
hanging in the moments collective.

Usage: python multihost_validate_worker.py <process_id> <num_processes> <port>
Prints one line: ABORTED pid=<i> nbad=<count from the message>
"""

import re
import sys


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices

    force_cpu_devices(2)
    jax.config.update("jax_enable_x64", True)

    from cuda_gmm_mpi_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )

    import numpy as np

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.validation import InvalidInputError

    n, d = 256, 3
    rng = np.random.default_rng(7)
    data = rng.normal(size=(n, d)).astype(np.float64)
    data[200, 1] = np.nan  # row 200 lands in the SECOND host's slice

    cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=32, dtype="float64")
    try:
        fit_gmm(data, 2, 2, config=cfg)
    except InvalidInputError as e:
        m = re.search(r"contains (\d+) non-finite", str(e))
        print(f"ABORTED pid={pid} nbad={m.group(1)}", flush=True)
        return 0
    print(f"NO-ERROR pid={pid}", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
