"""Compile & cost introspection: CompileWatch + ProfiledExecutable (round 15).

Contracts under test (telemetry/profiling.py, docs/OBSERVABILITY.md v2.2):

  * with a recorder active, ``fit_gmm`` activates a CompileWatch:
    ``compile`` events validate against the schema and
    ``run_summary.profile``'s site counts MATCH the executable caches'
    own observed compile counts -- plain EM, batched-restart, and
    serving (ScoringExecutor) paths;
  * cost/memory introspection rides the events where the backend
    provides analyses (CPU does: flops + bytes accessed + temp bytes);
  * with NO recorder, profiling is inert -- no watch activates, the
    proxies dispatch the plain jitted path, and the arithmetic is
    bit-identical to an instrumented run;
  * ProfiledExecutable keys its AOT cache by argument SIGNATURE (shape /
    dtype / weak-type), never by value: dynamic scalar args don't leak
    one compile per value.
"""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, GaussianMixture, fit_gmm, telemetry
from cuda_gmm_mpi_tpu.models.gmm import GMMModel
from cuda_gmm_mpi_tpu.serving import ScoringExecutor
from cuda_gmm_mpi_tpu.telemetry import (RunRecorder, read_stream,
                                        validate_stream)
from cuda_gmm_mpi_tpu.telemetry import profiling as tl_profiling

from .conftest import make_blobs


def _last_profile(recs):
    summaries = [r for r in recs if r["event"] == "run_summary"]
    assert summaries, "no run_summary in stream"
    prof = summaries[-1].get("profile")
    assert prof is not None, "recorder-active fit emitted no profile"
    return prof


def _aot_events(recs):
    return [r for r in recs if r["event"] == "compile"
            and r["source"] == "aot"]


def test_profile_compiles_match_em_cache(tmp_path, rng):
    """Plain fit path: run_summary.profile.compiles == the EM executable
    cache's own observed AOT build count, and every instrumented build
    emitted one enriched compile event."""
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    path = str(tmp_path / "m.jsonl")
    cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=128,
                    metrics_file=path)
    model = GMMModel(cfg)
    fit_gmm(data, 3, 3, cfg, model=model)

    recs = read_stream(path)
    assert validate_stream(recs) == []
    prof = _last_profile(recs)
    cache_compiles = sum(fn.aot_compiles
                         for fn in model._em_exec_cache.values())
    assert cache_compiles > 0
    assert prof["compiles"] == cache_compiles
    assert prof["sites"]["em"]["compiles"] == cache_compiles
    aot = _aot_events(recs)
    assert len(aot) == cache_compiles
    assert all(r["site"] == "em" for r in aot)
    # site builds are a subset of all XLA compiles, never double-counted
    assert prof["compiles"] <= prof["xla_compiles"]
    # seconds carry no such ordering: site walls include tracing/lowering
    # time the backend-compile listener never sees
    assert prof["compile_seconds"] > 0
    assert prof["xla_compile_seconds"] > 0
    assert sum(s["seconds"] for s in prof["sites"].values()) \
        == pytest.approx(prof["compile_seconds"], abs=1e-4)
    # CPU provides both analyses: cost + memory enrichment present
    assert prof["cost"]["flops"] > 0
    assert prof["cost"]["bytes_accessed"] > 0
    assert prof["memory"]["temp_bytes"] >= 0
    enriched = [r for r in aot if r.get("flops") is not None]
    assert enriched, "no compile event carried cost analysis"


def test_profile_compiles_match_batched_restart_cache(tmp_path, rng):
    """Batched-restart path: the vmapped restart executable's builds are
    attributed to the em_batched site and the cache count still matches
    the rollup."""
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    path = str(tmp_path / "m.jsonl")
    cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=128, n_init=2,
                    restart_batch_size=2, metrics_file=path)
    model = GMMModel(cfg)
    fit_gmm(data, 3, 2, cfg, model=model)

    recs = read_stream(path)
    assert validate_stream(recs) == []
    prof = _last_profile(recs)
    cache_compiles = sum(fn.aot_compiles
                         for fn in model._em_exec_cache.values())
    assert cache_compiles > 0
    assert prof["compiles"] == cache_compiles
    assert "em_batched" in prof["sites"]
    assert sum(s["compiles"] for s in prof["sites"].values()) \
        == prof["compiles"]
    assert len(_aot_events(recs)) == cache_compiles


def test_profile_serving_executor_counts(tmp_path, rng):
    """Serving path: ScoringExecutor's own compile counter and the watch
    rollup agree, warm traffic moves neither, and the compile events are
    tagged site=serve with the executor's cache key."""
    data, _ = make_blobs(rng, n=300, d=4, k=3, dtype=np.float64)
    gm = GaussianMixture(
        3, target_components=3,
        config=GMMConfig(min_iters=3, max_iters=3, chunk_size=128))
    gm.fit(data.astype(np.float32))
    state = gm.result_.state
    X = data.astype(np.float32)

    ex = ScoringExecutor(min_block=32, max_block=256)
    path = str(tmp_path / "serve.jsonl")
    with telemetry.use(RunRecorder(path)) as rec, rec:
        with tl_profiling.watch(rec) as w:
            ex.infer(state, X[:20])   # block 32: compile 1
            ex.infer(state, X[:60])   # block 64: compile 2
            ex.infer(state, X[:20])   # warm: no compile
            snap = w.snapshot()
    assert ex.compiles == 2
    assert snap["sites"]["serve"]["compiles"] == ex.compiles
    recs = read_stream(path)
    assert validate_stream(recs) == []
    aot = _aot_events(recs)
    assert len(aot) == 2
    assert all(r["site"] == "serve" and r.get("key") for r in aot)


def test_no_recorder_profiling_inert_and_bit_identical(tmp_path, rng):
    """The byte-identity gate: without a recorder no watch activates,
    and instrumenting a run changes nothing about the arithmetic."""
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    base = dict(min_iters=3, max_iters=3, chunk_size=128, seed=0)
    r0 = fit_gmm(data, 3, 3, GMMConfig(**base))
    assert tl_profiling.active() is None
    assert not telemetry.current().active

    path = str(tmp_path / "m.jsonl")
    r1 = fit_gmm(data, 3, 3, GMMConfig(metrics_file=path, **base))
    assert tl_profiling.active() is None  # watch closed with the fit
    assert r1.final_loglik == r0.final_loglik  # bit-identical, not approx
    np.testing.assert_array_equal(np.asarray(r1.means),
                                  np.asarray(r0.means))
    # ...and the instrumented run really was instrumented
    assert _last_profile(read_stream(path))["compiles"] > 0


def test_compile_events_buffer_until_stream_head(tmp_path):
    """Stream-ordering contract: compiles observed before the owning
    loop writes its first record (prologue jits) buffer inside the
    watch and flush BEHIND the head, so run_start stays record 0."""
    path = str(tmp_path / "m.jsonl")
    with telemetry.use(RunRecorder(path)) as rec, rec:
        with tl_profiling.watch(rec) as w:
            w.observe_site("em", 0.5)    # pre-head: buffered, not written
            rec.emit("run_start", start_k=3)
            w.observe_site("em", 0.25)   # head exists: drains, then emits
    recs = read_stream(path)
    assert [r["event"] for r in recs] == ["run_start", "compile", "compile"]
    # observation order survives the buffer
    assert [r["seconds"] for r in recs[1:]] == [0.5, 0.25]


def test_watch_out_of_order_exit_keeps_active_watch():
    """Concurrent watches (a fit in one thread, serve in another) may
    exit in any order: the earlier-entered watch exiting first must not
    tear down -- and its later exit must not resurrect -- the other."""
    cm_a, cm_b = tl_profiling.watch(), tl_profiling.watch()
    w_a = cm_a.__enter__()
    w_b = cm_b.__enter__()
    assert tl_profiling.active() is w_b
    cm_a.__exit__(None, None, None)      # out-of-order: a exits first
    assert tl_profiling.active() is w_b
    cm_b.__exit__(None, None, None)
    assert tl_profiling.active() is None
    assert w_a is not w_b


def test_profiled_executable_signature_keying():
    """AOT cache keys are argument signatures: same shape/dtype with
    different VALUES reuses one executable; a new shape compiles anew;
    without a watch the proxy is a transparent passthrough."""
    import jax
    import jax.numpy as jnp

    fn = tl_profiling.ProfiledExecutable(jax.jit(lambda x, s: x * s),
                                         site="em")
    # no watch: plain dispatch, nothing counted
    np.testing.assert_allclose(
        np.asarray(fn(jnp.ones((4,), jnp.float32), jnp.float32(2.0))),
        2.0 * np.ones(4, np.float32))
    assert fn.aot_compiles == 0

    with tl_profiling.watch() as w:
        a = fn(jnp.ones((4,), jnp.float32), jnp.float32(2.0))
        b = fn(jnp.full((4,), 3.0, jnp.float32), jnp.float32(5.0))
        assert fn.aot_compiles == 1  # value change, same signature
        c = fn(jnp.ones((8,), jnp.float32), jnp.float32(2.0))
        assert fn.aot_compiles == 2  # shape change: one more build
    np.testing.assert_allclose(np.asarray(a), 2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(b), 15.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(c), 2.0 * np.ones(8))
    assert w.snapshot()["sites"]["em"]["compiles"] == 2
