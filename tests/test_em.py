"""EM loop integration: oracle parity, monotone log-likelihood, convergence."""

import jax
import jax.numpy as jnp
import numpy as np

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters, seed_clusters_host

from .reference_impl import np_em


def run_em(data, k, min_iters, max_iters, dtype=np.float64, **cfg_kw):
    cfg = GMMConfig(min_iters=min_iters, max_iters=max_iters,
                    chunk_size=256, dtype="float64", **cfg_kw)
    model = GMMModel(cfg)
    chunks, wts = chunk_events(data.astype(dtype), cfg.chunk_size)
    state = seed_clusters(jnp.asarray(data.astype(dtype)), k)
    eps = convergence_epsilon(data.shape[0], data.shape[1])
    return model.run_em(state, jnp.asarray(chunks), jnp.asarray(wts), eps)


def test_em_matches_numpy_oracle(blobs):
    """5 full EM iterations bit-track the float64 NumPy oracle."""
    data, _ = blobs
    k = 4
    state, ll, iters = run_em(data, k, 5, 5)
    params, lls, _ = np_em(data, k, 5)
    assert int(iters) == 5
    np.testing.assert_allclose(float(ll), lls[-1], rtol=1e-9)
    np.testing.assert_allclose(np.asarray(state.means), params["means"],
                               rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(np.asarray(state.R), params["R"], rtol=1e-6,
                               atol=1e-8)
    np.testing.assert_allclose(np.asarray(state.N), params["N"], rtol=1e-8)
    np.testing.assert_allclose(np.asarray(state.pi), params["pi"], rtol=1e-8)


def test_loglik_monotone(blobs):
    """EM guarantees monotone non-decreasing log-likelihood (the reference
    never asserts this; SURVEY.md SS4 calls it out as a required test)."""
    data, _ = blobs
    _, lls, _ = np_em(data, 4, 12)
    # oracle monotone (sanity of the test itself)
    assert all(b >= a - 1e-7 for a, b in zip(lls, lls[1:]))
    # jax path: track loglik across single-step runs
    prev = None
    for iters in range(1, 8):
        _, ll, _ = run_em(data, 4, iters, iters)
        ll = float(ll)
        if prev is not None:
            assert ll >= prev - 1e-6
        prev = ll


def test_convergence_early_exit(blobs):
    """min_iters=1 lets the epsilon test stop well before max_iters on
    well-separated data (the reference ships MIN==MAX which disables this;
    we verify the runtime-configurable path)."""
    data, _ = blobs
    state, ll, iters = run_em(data, 4, 1, 200)
    assert 1 <= int(iters) < 200


def test_diag_only_em_runs(blobs):
    data, _ = blobs
    state, ll, iters = run_em(data, 4, 3, 3, diag_only=True)
    R = np.asarray(state.R)
    off = R - np.stack([np.diag(np.diag(R[c])) for c in range(R.shape[0])])
    assert np.abs(off).max() == 0.0
    assert np.isfinite(float(ll))


def test_em_float32_close_to_oracle(blobs):
    data, _ = blobs
    k = 4
    cfg = GMMConfig(min_iters=5, max_iters=5, chunk_size=256, dtype="float32")
    model = GMMModel(cfg)
    x32 = data.astype(np.float32)
    chunks, wts = chunk_events(x32, cfg.chunk_size)
    state = seed_clusters(jnp.asarray(x32), k)
    eps = convergence_epsilon(*data.shape)
    state, ll, _ = model.run_em(state, jnp.asarray(chunks), jnp.asarray(wts), eps)
    params, lls, _ = np_em(data, k, 5)
    np.testing.assert_allclose(float(ll), lls[-1], rtol=2e-5)
    np.testing.assert_allclose(np.asarray(state.means), params["means"],
                               rtol=2e-3, atol=2e-3)


def test_chunk_size_invariance(blobs):
    """The chunk grid is an execution detail: the same fit across chunk
    sizes -- including ragged tails that exercise the zero-weight padding
    row -- must agree to float64 reduction-order tolerance."""
    data, _ = blobs  # n=2000
    results = []
    for chunk in (64, 300, 2000):  # 300 leaves a padded ragged tail
        cfg = GMMConfig(min_iters=5, max_iters=5, chunk_size=chunk,
                        dtype="float64")
        chunks, wts = chunk_events(data, cfg.chunk_size)
        state = seed_clusters_host(data, 4)
        s, ll, _ = GMMModel(cfg).run_em(state, jnp.asarray(chunks),
                                        jnp.asarray(wts),
                                        convergence_epsilon(*data.shape))
        results.append((float(ll), np.asarray(s.means)[:4]))
    ll0, m0 = results[0]
    for ll, m in results[1:]:
        np.testing.assert_allclose(ll, ll0, rtol=1e-11)
        np.testing.assert_allclose(m, m0, rtol=1e-9, atol=1e-9)


def test_precompute_features_bitwise_identical(blobs):
    """precompute_features hoists the [C, B, F] features out of the EM loop
    but feeds the SAME values through the SAME matmuls: the whole fit --
    plain model, sharded model, and the fused sweep -- must be bit-identical
    with the flag on."""
    import pytest

    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    data, _ = blobs
    kw = dict(min_iters=5, max_iters=5, chunk_size=256, dtype="float64")

    for extra in (dict(), dict(mesh_shape=(4, 2)), dict(fused_sweep=True)):
        r0 = fit_gmm(data, 5, 2, GMMConfig(**kw, **extra))
        r1 = fit_gmm(data, 5, 2,
                     GMMConfig(precompute_features=True, **kw, **extra))
        assert r1.ideal_num_clusters == r0.ideal_num_clusters, extra
        np.testing.assert_array_equal(np.asarray(r1.means),
                                      np.asarray(r0.means), err_msg=str(extra))
        np.testing.assert_array_equal(r1.final_loglik, r0.final_loglik,
                                      err_msg=str(extra))

    # Guards: the flag is meaningless off the full-covariance in-memory
    # paths and must say so. 'packed' is a supported layout now (the hoist
    # stores the [N, D(D+1)/2] upper triangle; tests/test_bucketing.py
    # asserts its per-layout bit-identity); 'centered' has no
    # loop-invariant feature matrix to hoist.
    with pytest.raises(ValueError, match="full-covariance"):
        GMMConfig(precompute_features=True, diag_only=True)
    GMMConfig(precompute_features=True, quad_mode="packed")  # allowed
    with pytest.raises(ValueError, match="expanded"):
        GMMConfig(precompute_features=True, quad_mode="centered")
    with pytest.raises(ValueError, match="Pallas"):
        GMMConfig(precompute_features=True, use_pallas="always")
    with pytest.raises(ValueError, match="stream"):
        GMMConfig(precompute_features=True, stream_events=True)
