"""Unified timeline export (rev v2.3; docs/OBSERVABILITY.md "Timeline
export"): `gmm timeline` -> Chrome trace-event JSON + clock alignment.

Contracts:
- the recorder anchors its own stream: the FIRST record carries an
  atomically-sampled ``clock``/``clock0`` wall+mono pair and every
  heartbeat refreshes ``clock`` (both directions schema-checked);
- two per-rank streams with wildly different (and skewed) mono bases
  merge onto ONE wall timebase, aligned within the heartbeat-anchor
  tolerance the export itself reports;
- a fit stream and a serve stream export together, with flow arrows
  joining a client's ``serve_request`` slice to the server-side
  ``serve_route`` span tree that answered it (same trace_id);
- pre-v2.3 streams (no clock anchors) still export, loudly marked
  ``alignment: estimated``; streams with no ``mono_s`` at all fall back
  to raw ``ts``;
- ``--validate`` is a real structural oracle: it passes this exporter's
  output and fails hand-broken documents (unknown phases, negative
  durations, backwards per-track timestamps, unpaired flows);
- the CLI honors the diff-family exit contract: 0 exported, 2 usage /
  unreadable.
"""

import io
import json
import os

import pytest

from cuda_gmm_mpi_tpu.telemetry import RunRecorder, schema
from cuda_gmm_mpi_tpu.telemetry import timeline as tl
from cuda_gmm_mpi_tpu.telemetry.timeline import (build_timeline,
                                                 fit_alignment,
                                                 summarize_trace,
                                                 timeline_main,
                                                 validate_trace)


def _mk(event, ts, mono, **fields):
    base = {"event": event, "schema": schema.SCHEMA_VERSION,
            "ts": round(ts, 6), "mono_s": round(mono, 6),
            "run_id": "r1", "process": 0}
    base.update(fields)
    return base


def _write(path, records):
    with open(path, "w", encoding="utf-8") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _clock(ts, mono):
    return {"wall": round(ts, 6), "mono": round(mono, 6)}


def _rank_stream(rank, mono_base, skew=0.0, wall_base=1000.0):
    """A fit-shaped stream whose mono clock starts at ``mono_base`` and
    drifts by ``skew`` seconds per second against the wall clock."""

    def mono(t):  # t = seconds since run start, on the WALL clock
        return mono_base + t * (1.0 + skew)

    recs = [_mk("run_start", wall_base, mono(0.0), rank=rank,
                platform="cpu", num_events=100, num_dimensions=4,
                start_k=4, clock=_clock(wall_base, mono(0.0)),
                clock0=_clock(wall_base, mono(0.0)))]
    for i in range(4):
        t = 1.0 + i
        recs.append(_mk("em_iter", wall_base + t, mono(t), rank=rank,
                        k=4, iter=i, loglik=-5.0 + i, wall_s=0.5))
    for t in (2.0, 4.0):
        recs.append(_mk("heartbeat", wall_base + t, mono(t), rank=rank,
                        phase="em", elapsed_s=t, rss_bytes=1e8 + t,
                        clock=_clock(wall_base + t, mono(t))))
    recs.append(_mk("run_summary", wall_base + 5.0, mono(5.0), rank=rank,
                    ideal_k=4, score=1.0, final_loglik=-1.0,
                    total_iters=4, wall_s=5.0))
    return recs


# -------------------------------------------- recorder clock anchoring


def test_recorder_anchors_first_record_and_heartbeats():
    """The v2.3 emit contract: clock+clock0 on the stream's first
    record, a fresh clock on every heartbeat, nothing on other records
    -- both directions, so the anchors can't silently spread or dry up."""
    buf = io.StringIO()
    rec = RunRecorder(stream=buf)
    rec.emit("run_start", platform="cpu", num_events=1,
             num_dimensions=1, start_k=1, epsilon=1e-3)
    rec.emit("em_iter", k=1, iter=0, loglik=-1.0, wall_s=0.1,
             delta=0.0, epsilon=1e-3, timing={})
    rec.heartbeat("em")
    records = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    first, em, hb = records
    assert first["event"] == "run_start"
    for field in ("clock", "clock0"):
        pair = first[field]
        assert isinstance(pair["wall"], float)
        assert isinstance(pair["mono"], float)
    # clock0 is the construction-time anchor: no later than emit time.
    assert first["clock0"]["mono"] <= first["clock"]["mono"]
    assert "clock" not in em and "clock0" not in em
    assert hb["event"] == "heartbeat"
    assert "clock" in hb and "clock0" not in hb
    assert hb["clock"]["mono"] >= first["clock"]["mono"]
    # The stream passes schema validation with the anchors on it.
    assert not schema.validate_stream(records)


def test_schema_rejects_malformed_clock_pairs():
    good = _mk("heartbeat", 1000.0, 10.0, phase="em", elapsed_s=1.0,
               clock=_clock(1000.0, 10.0))
    assert not schema.validate_record(good)
    bad_shape = dict(good, clock=[1000.0, 10.0])
    assert any("clock" in e for e in schema.validate_record(bad_shape))
    bad_field = dict(good, clock={"wall": 1000.0, "mono": "ten"})
    assert any("mono" in e for e in schema.validate_record(bad_field))
    bad_bool = dict(good, clock0={"wall": True, "mono": 10.0})
    assert any("clock0" in e for e in schema.validate_record(bad_bool))


# ------------------------------------------------------ alignment maths


def test_fit_alignment_recovers_offset_and_skew():
    """Anchors from a stream whose mono clock starts 490s behind the
    wall AND drifts 1ms/s: the fitted a*mono+b mapping must land every
    anchor within the residual the fit itself reports (and that residual
    must be tiny -- the anchors are exact here)."""
    recs = _rank_stream(0, mono_base=510.0, skew=0.001)
    align = fit_alignment(recs)
    assert align["mode"] == "clock"
    assert align["anchors"] == 3          # run head + two heartbeats
    assert abs(align["a"] - 1.0 / 1.001) < 1e-6
    assert align["residual_s"] < 1e-3
    # The mapping reproduces the generating wall times.
    for r in recs:
        wall = tl._wall_of(r, align)
        assert abs(wall - r["ts"]) < 1e-3


def test_fit_alignment_falls_back_estimated_then_wall():
    pre_v23 = [_mk("run_start", 1000.0, 10.0, platform="cpu",
                   num_events=1, num_dimensions=1, start_k=1),
               _mk("em_iter", 1001.0, 11.0, k=1, iter=0, loglik=-1.0,
                   wall_s=0.5)]
    align = fit_alignment(pre_v23)
    assert align["mode"] == "estimated"
    assert align["anchors"] == 2          # per-record (ts, mono_s) pairs
    assert abs(tl._wall_of(pre_v23[1], align) - 1001.0) < 1e-6
    no_mono = [{"event": "em_iter", "schema": 1, "ts": 1001.0,
                "run_id": "r1", "process": 0, "k": 1, "iter": 0,
                "loglik": -1.0, "wall_s": 0.5}]
    align = fit_alignment(no_mono)
    assert align["mode"] == "wall"
    assert tl._wall_of(no_mono[0], align) == 1001.0


def test_fit_alignment_clamps_garbage_slope():
    """Anchors implying a 2x mono-vs-wall rate are corrupt, not drift:
    the fit must refuse the slope (keep a=1) rather than smear events."""
    recs = [_mk("run_start", 1000.0, 10.0, num_events=1,
                num_dimensions=1, start_k=1, platform="cpu",
                clock=_clock(1000.0, 10.0), clock0=_clock(1000.0, 10.0)),
            _mk("heartbeat", 1010.0, 15.0, phase="em", elapsed_s=10.0,
                clock=_clock(1010.0, 15.0))]
    align = fit_alignment(recs)
    assert align["a"] == 1.0


# ------------------------------------------------- two-rank merge (e2e)


def test_two_rank_skewed_streams_align_on_one_timebase(tmp_path):
    """The acceptance scenario: rank streams with mono bases 500s apart
    (plus drift on one) merge into a validate-clean trace where
    same-wall-moment events from both ranks land at the same exported
    timestamp, within the per-stream residual tolerance."""
    d = tmp_path / "streams"
    d.mkdir()
    _write(str(d / "rank0.jsonl"), _rank_stream(0, mono_base=10.0))
    _write(str(d / "rank1.jsonl"),
           _rank_stream(1, mono_base=510.0, skew=0.0005))
    doc = build_timeline([str(d)])
    assert validate_trace(doc) == []
    meta = doc["metadata"]
    assert meta["alignment"] == "clock"
    assert [s["rank"] for s in meta["streams"]] == [0, 1]
    tolerance_s = max(s["residual_s"] for s in meta["streams"]) + 1e-3
    # Each rank's iter=i em slice was generated at the SAME wall time;
    # after alignment their exported ts must agree within tolerance.
    slices = [e for e in doc["traceEvents"] if e.get("cat") == "em_iter"]
    by_rank = {}
    for e in slices:
        by_rank.setdefault(e["pid"], []).append(e)
    assert len(by_rank) == 2
    a, b = (sorted(evs, key=lambda e: e["ts"])
            for evs in by_rank.values())
    assert len(a) == len(b) == 4
    for ea, eb in zip(a, b):
        assert abs(ea["ts"] - eb["ts"]) <= tolerance_s * 1e6
    # Counters rode along: one RSS track per rank.
    rss = [e for e in doc["traceEvents"] if e.get("ph") == "C"
           and e["name"] == "host RSS bytes"]
    assert {e["pid"] for e in rss} == set(by_rank)


def test_pre_v23_streams_export_as_estimated(tmp_path, capsys):
    """Streams recorded before the clock anchors still export -- via
    per-record (ts, mono_s) pairs -- and BOTH the document metadata and
    the CLI's stderr banner say so."""
    path = str(tmp_path / "old.jsonl")
    recs = _rank_stream(0, mono_base=10.0)
    for r in recs:
        r.pop("clock", None)
        r.pop("clock0", None)
    _write(path, recs)
    doc = build_timeline([path])
    assert doc["metadata"]["alignment"] == "estimated"
    assert validate_trace(doc) == []
    assert timeline_main([path, "--validate"]) == 0
    err = capsys.readouterr().err
    assert "alignment: estimated" in err


# --------------------------------------------------- fit + serve flows


def test_fit_and_serve_streams_join_via_flow_arrows(tmp_path):
    """A client-side serve_request slice and the server-side serve_route
    span tree carry the same trace_id; exporting the two streams
    together must join them with a PAIRED s/f flow arrow."""
    fit = str(tmp_path / "fit.jsonl")
    _write(fit, _rank_stream(0, mono_base=10.0))
    tid = "a1b2c3d4e5f60718"
    serve = str(tmp_path / "serve.jsonl")
    base = 1002.0
    serve_recs = [
        _mk("heartbeat", base, 900.0, path="serve", phase="serve",
            elapsed_s=0.0, clock=_clock(base, 900.0),
            clock0=_clock(base, 900.0)),
        _mk("span", base + 0.2, 900.2, path="serve", name="prepare",
            span_id="b" * 16, parent_id="a" * 16, trace_id=tid,
            t0_mono_s=900.11, duration_s=0.04, status="ok"),
        _mk("span", base + 0.3, 900.3, path="serve", name="serve_route",
            span_id="a" * 16, trace_id=tid, t0_mono_s=900.1,
            duration_s=0.2, status="ok"),
        _mk("serve_request", base + 0.35, 900.35, path="serve",
            model="m", op="score", n=8, ok=True, latency_ms=250.0,
            trace_id=tid),
        _mk("serve_summary", base + 1.0, 901.0, path="serve",
            requests=1, rows=8),
    ]
    _write(serve, serve_recs)
    doc = build_timeline([fit, serve])
    assert validate_trace(doc) == []
    assert doc["metadata"]["flow_count"] == 1
    starts = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
    ends = [e for e in doc["traceEvents"] if e.get("ph") == "f"]
    assert len(starts) == len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"] == tid
    # The finish side binds to the serve_route span's track, enclosing
    # (bp: "e") so Perfetto attaches it to the slice, not an instant.
    route = [e for e in doc["traceEvents"]
             if e.get("cat") == "span" and e["name"] == "serve_route"]
    assert ends[0]["pid"] == route[0]["pid"]
    assert ends[0]["bp"] == "e"
    # Spans nest: prepare sits inside serve_route on the same track.
    prep = [e for e in doc["traceEvents"]
            if e.get("cat") == "span" and e["name"] == "prepare"][0]
    assert prep["pid"] == route[0]["pid"]
    assert prep["ts"] >= route[0]["ts"]
    assert prep["ts"] + prep["dur"] <= route[0]["ts"] + route[0]["dur"] \
        + 1.0


def test_unmatched_trace_ids_produce_no_dangling_flows(tmp_path):
    """A serve_request whose trace_id has no server-side span (tracing
    off on the server) must produce NO flow start -- an unpaired `s` is
    a validation error by design."""
    path = str(tmp_path / "serve.jsonl")
    _write(path, [
        _mk("heartbeat", 1000.0, 10.0, path="serve", phase="serve",
            elapsed_s=0.0, clock=_clock(1000.0, 10.0),
            clock0=_clock(1000.0, 10.0)),
        _mk("serve_request", 1000.5, 10.5, path="serve", model="m",
            op="score", n=8, ok=True, latency_ms=100.0,
            trace_id="deadbeefdeadbeef"),
    ])
    doc = build_timeline([path])
    assert validate_trace(doc) == []
    assert doc["metadata"]["flow_count"] == 0
    assert not [e for e in doc["traceEvents"] if e.get("ph") in "sf"]


# ------------------------------------------------- validate (the oracle)


def test_validate_trace_catches_structural_breakage():
    base = {"ph": "X", "name": "x", "cat": "c", "pid": 1, "tid": 1,
            "ts": 1.0, "dur": 2.0, "args": {}}
    ok = {"traceEvents": [dict(base)], "displayTimeUnit": "ms"}
    assert validate_trace(ok) == []
    assert validate_trace([]) != []                     # not an object
    assert validate_trace({"traceEvents": 3}) != []     # not a list
    assert any("no events" in e for e in
               validate_trace({"traceEvents": []}))
    assert any("unknown ph" in e for e in validate_trace(
        {"traceEvents": [dict(base, ph="Z")]}))
    assert any("bad dur" in e for e in validate_trace(
        {"traceEvents": [dict(base, dur=-1.0)]}))
    assert any("bad ts" in e for e in validate_trace(
        {"traceEvents": [dict(base, ts=-5.0)]}))
    assert any("backwards" in e for e in validate_trace(
        {"traceEvents": [dict(base, ts=9.0), dict(base, ts=1.0)]}))
    # Different tracks may interleave timestamps freely.
    assert validate_trace({"traceEvents": [
        dict(base, ts=9.0), dict(base, ts=1.0, tid=2)]}) == []
    assert any("E without open B" in e for e in validate_trace(
        {"traceEvents": [{"ph": "E", "pid": 1, "tid": 1, "ts": 1.0}]}))
    assert any("unmatched B" in e for e in validate_trace(
        {"traceEvents": [{"ph": "B", "name": "b", "pid": 1, "tid": 1,
                          "ts": 1.0}]}))
    assert any("counter args" in e for e in validate_trace(
        {"traceEvents": [{"ph": "C", "name": "c", "pid": 1, "ts": 1.0,
                          "args": {"v": "NaN-ish"}}]}))
    assert any("start without finish" in e for e in validate_trace(
        {"traceEvents": [dict(base),
                         {"ph": "s", "id": "t1", "pid": 1, "tid": 1,
                          "ts": 1.0}]}))
    assert any("finish without start" in e for e in validate_trace(
        {"traceEvents": [dict(base),
                         {"ph": "f", "bp": "e", "id": "t1", "pid": 1,
                          "tid": 1, "ts": 1.0}]}))
    assert any("precedes" in e for e in validate_trace(
        {"traceEvents": [dict(base),
                         {"ph": "s", "id": "t1", "pid": 1, "tid": 1,
                          "ts": 5.0},
                         {"ph": "f", "bp": "e", "id": "t1", "pid": 1,
                          "tid": 1, "ts": 1.0}]}))


# ------------------------------------------------------------- CLI / exit


def test_timeline_cli_exports_and_validates(tmp_path, capsys):
    d = tmp_path / "streams"
    d.mkdir()
    _write(str(d / "rank0.jsonl"), _rank_stream(0, mono_base=10.0))
    _write(str(d / "rank1.jsonl"), _rank_stream(1, mono_base=510.0))
    out = str(tmp_path / "run.trace.json")
    assert timeline_main([str(d), "-o", out, "--validate",
                          "--json"]) == 0
    captured = capsys.readouterr()
    summary = json.loads(captured.out.strip().splitlines()[-1])
    assert summary["validate_ok"] is True
    assert summary["alignment"] == "clock"
    assert summary["events"] > 0 and summary["pids"] == 2
    assert summary["out"] == out
    doc = json.load(open(out, encoding="utf-8"))
    assert validate_trace(doc) == []
    assert summarize_trace(doc)["events"] == summary["events"]
    # Perfetto needs named processes to be navigable.
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert len(names) == 2 and any("rank 1" in n for n in names)


def test_timeline_cli_default_output_path(tmp_path, capsys):
    path = str(tmp_path / "fit.jsonl")
    _write(path, _rank_stream(0, mono_base=10.0))
    assert timeline_main([path]) == 0
    capsys.readouterr()
    assert os.path.exists(str(tmp_path / "fit.trace.json"))


def test_timeline_cli_exit_2_on_usage_and_unreadable(tmp_path, capsys):
    assert timeline_main([]) == 2                       # usage
    missing = str(tmp_path / "nope.jsonl")
    assert timeline_main([missing]) == 2                # unreadable
    empty = str(tmp_path / "empty.jsonl")
    _write(empty, [])
    assert timeline_main([empty]) == 2                  # empty stream
    notastream = str(tmp_path / "not.jsonl")
    with open(notastream, "w", encoding="utf-8") as fh:
        fh.write('{"foo": 1}\n')
    assert timeline_main([notastream]) == 2             # no event records
    capsys.readouterr()


def test_timeline_routes_through_gmm_cli(tmp_path, capsys):
    from cuda_gmm_mpi_tpu.cli import main

    path = str(tmp_path / "fit.jsonl")
    _write(path, _rank_stream(0, mono_base=10.0))
    assert main(["timeline", path, "--validate"]) == 0
    out = capsys.readouterr().out
    assert "alignment: clock" in out and "validate: clean" in out
