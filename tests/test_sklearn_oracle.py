"""EM trajectory parity vs scikit-learn's GaussianMixture (external oracle).

SURVEY.md §4: the reference's correctness was established against Bouman's
sequential `cluster` program; here the independent oracle is sklearn. With
matched initialization (same means, uniform weights, identity covariances),
zero regularization on both sides, and N EM iterations, the parameters after
N M-steps must agree for every covariance family -- this validates the whole
E+M pipeline (including the spherical/tied constraints) against an
implementation that shares no code or design with ours.
"""

import numpy as np
import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn.mixture import GaussianMixture as SkGMM  # noqa: E402

from cuda_gmm_mpi_tpu import GaussianMixture  # noqa: E402


def _sk_precisions_init(cov_type, k, d):
    if cov_type == "full":
        return np.broadcast_to(np.eye(d), (k, d, d)).copy()
    if cov_type == "tied":
        return np.eye(d)
    if cov_type == "diag":
        return np.ones((k, d))
    return np.ones(k)  # spherical


def _sk_covariances(sk, cov_type, k, d):
    """sklearn covariances_ normalized to [K, D, D]."""
    c = sk.covariances_
    if cov_type == "full":
        return c
    if cov_type == "tied":
        return np.broadcast_to(c, (k, d, d))
    if cov_type == "diag":
        return np.stack([np.diag(row) for row in c])
    return np.stack([np.eye(d) * v for v in c])  # spherical


@pytest.mark.parametrize("cov_type", ["full", "diag", "spherical", "tied"])
def test_em_trajectory_matches_sklearn(rng, cov_type):
    k, d, n, iters = 3, 4, 1500, 7
    centers = rng.normal(scale=6.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float64)

    sk = SkGMM(
        n_components=k, covariance_type=cov_type, max_iter=iters, tol=0.0,
        reg_covar=0.0, means_init=centers,
        weights_init=np.full(k, 1.0 / k),
        precisions_init=_sk_precisions_init(cov_type, k, d),
    )
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # tol=0 never "converges"
        sk.fit(data)

    gm = GaussianMixture(
        k, target_components=k, means_init=centers,
        covariance_type=cov_type, min_iters=iters, max_iters=iters,
        chunk_size=512, dtype="float64",
        # zero out the avgvar diagonal loading to match reg_covar=0
        covariance_dynamic_range=1e30,
    ).fit(data)

    np.testing.assert_allclose(gm.weights_, sk.weights_, rtol=1e-8,
                               atol=1e-10)
    np.testing.assert_allclose(gm.means_, sk.means_, rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(
        gm.covariances_, _sk_covariances(sk, cov_type, k, d),
        rtol=1e-7, atol=1e-8)
    # per-event evidence agrees too (score_samples is sklearn-compatible)
    np.testing.assert_allclose(gm.score_samples(data),
                               sk.score_samples(data), rtol=1e-7, atol=1e-8)
    # information criteria: same family-aware free-parameter counts
    np.testing.assert_allclose(gm.bic(data), sk.bic(data), rtol=1e-9)
    np.testing.assert_allclose(gm.aic(data), sk.aic(data), rtol=1e-9)
