"""CLI end-to-end: reference argv semantics, outputs, error codes."""

import subprocess
import sys

import numpy as np
import pytest

from cuda_gmm_mpi_tpu.cli import main
from cuda_gmm_mpi_tpu.io.readers import write_bin

from .conftest import make_blobs


@pytest.fixture
def csv_file(tmp_path, rng):
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    p = tmp_path / "events.csv"
    header = ",".join(f"d{i}" for i in range(3))
    rows = "\n".join(",".join(f"{v:.6f}" for v in row) for row in data)
    p.write_text(header + "\n" + rows + "\n")
    return str(p)


def run_cli(args):
    return main(args)


def test_version_flag():
    from cuda_gmm_mpi_tpu import __version__

    r = subprocess.run(
        [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "--version"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    assert r.stdout.strip() == f"gmm {__version__}"


def test_version_matches_pyproject():
    """_version.py and pyproject.toml are bumped together (the version
    deliberately lives in exactly these two places)."""
    import os
    import re

    from cuda_gmm_mpi_tpu import __version__

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "pyproject.toml")
    try:
        import tomllib  # stdlib only from Python 3.11
    except ImportError:
        m = re.search(r'^version\s*=\s*"([^"]+)"',
                      open(path, encoding="utf-8").read(), re.M)
        assert m, "no version field in pyproject.toml"
        assert m.group(1) == __version__
        return
    with open(path, "rb") as fh:
        meta = tomllib.load(fh)
    assert meta["project"]["version"] == __version__


def test_cli_end_to_end(csv_file, tmp_path):
    out = str(tmp_path / "out")
    rc = run_cli(["3", csv_file, out, "3",
                  "--min-iters=3", "--max-iters=3", "--chunk-size=256"])
    assert rc == 0
    summary = (tmp_path / "out.summary").read_text()
    assert summary.count("Cluster #") == 3
    assert "Probability:" in summary and "R Matrix:" in summary
    with open(csv_file) as f:
        n_events = len(f.read().splitlines()) - 1  # minus header
    results = (tmp_path / "out.results").read_text().splitlines()
    assert len(results) == n_events
    data_part, memb_part = results[0].split("\t")
    assert len(data_part.split(",")) == 3
    assert len(memb_part.split(",")) == 3


def test_cli_rejects_nonfinite_input(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,b\n1.0,2.0\nnan,3.0\n4.0,5.0\n")
    assert run_cli(["2", str(p), str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2"]) == 1
    # values finite in the reader's float64 but Inf in compute float32 are
    # caught too (validation runs after the dtype cast)
    p2 = tmp_path / "overflow.csv"
    p2.write_text("a,b\n1.0,2.0\n1e39,3.0\n4.0,5.0\n")
    assert run_cli(["2", str(p2), str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2"]) == 1
    # Opting out of input validation no longer reproduces the reference's
    # silent-atof poisoning: the in-loop health bitmask catches the NaN
    # loglik, the escalation ladder cannot fix genuinely poisoned DATA,
    # and the run fails loudly (exit 70, EX_SOFTWARE, diagnostic bundle,
    # no model written) instead of returning NaN parameters
    # (docs/ROBUSTNESS.md; docs/API.md exit-code table).
    assert run_cli(["2", str(p), str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2",
                    "--no-validate-input"]) == 70
    assert not (tmp_path / "o.summary").exists()
    # recovery='off' raises the same loud failure without burning ladder
    # attempts on unfixable data.
    assert run_cli(["2", str(p), str(tmp_path / "o2"), "2",
                    "--min-iters=2", "--max-iters=2",
                    "--no-validate-input", "--recovery=off"]) == 70


def test_cli_predict_from_validates_input(tmp_path, csv_file):
    out = str(tmp_path / "m")
    assert run_cli(["3", csv_file, out, "3", "--min-iters=2",
                    "--max-iters=2", "--chunk-size=256"]) == 0
    bad = tmp_path / "bad.csv"
    bad.write_text("a,b,c\n1.0,2.0,3.0\ninf,0.0,1.0\n")
    assert run_cli(["1", str(bad), str(tmp_path / "p"),
                    f"--predict-from={out}.summary"]) == 1
    assert run_cli(["1", str(bad), str(tmp_path / "p"),
                    f"--predict-from={out}.summary",
                    "--no-validate-input"]) == 0


def test_cli_sweep_log(csv_file, tmp_path):
    import json

    log = tmp_path / "sweep.jsonl"
    rc = run_cli(["4", csv_file, str(tmp_path / "o"), "2",
                  "--min-iters=2", "--max-iters=2", "--chunk-size=256",
                  f"--sweep-log={log}"])
    assert rc == 0
    rows = [json.loads(l) for l in log.read_text().splitlines()]
    assert [r["num_clusters"] for r in rows] == [4, 3, 2]
    assert all(r["em_iters"] == 2 and np.isfinite(r["loglik"])
               and np.isfinite(r["score"])
               and r["criterion"] == "rissanen" for r in rows)
    # unwritable path fails fast, before any fitting
    assert run_cli(["4", csv_file, str(tmp_path / "o2"), "2",
                    f"--sweep-log={tmp_path}/no/such/dir/s.jsonl"]) == 1
    # meaningless with --predict-from: rejected, not silently ignored
    assert run_cli(["4", csv_file, str(tmp_path / "o2"),
                    f"--predict-from={tmp_path}/o.summary",
                    f"--sweep-log={log}"]) == 1
    for extra in ("--n-init=3", "--fused-sweep", "--checkpoint-dir=ck"):
        assert run_cli(["4", csv_file, str(tmp_path / "o2"),
                        f"--predict-from={tmp_path}/o.summary", extra]) == 1
    # a failed pre-fit abort must not leave a zero-byte sweep-log artifact
    s2 = tmp_path / "s2.jsonl"
    assert run_cli(["4", csv_file, str(tmp_path / "o3"), "2",
                    f"--sweep-log={s2}",
                    f"--init-from={tmp_path}/nope.summary"]) == 1
    assert not s2.exists()


def test_cli_init_from(csv_file, tmp_path):
    """--init-from warm-starts fitting from a saved model's means."""
    out = str(tmp_path / "m")
    assert run_cli(["3", csv_file, out, "3", "--min-iters=40",
                    "--max-iters=40", "--chunk-size=256"]) == 0
    out2 = str(tmp_path / "m2")
    assert run_cli(["3", csv_file, out2, "3", "--min-iters=4",
                    "--max-iters=4", "--chunk-size=256",
                    f"--init-from={out}.summary"]) == 0
    # warm-started from a converged optimum: means stay put
    def means(p):
        return np.array([[float(v) for v in l.split()[1:]]
                         for l in open(p) if l.startswith("Means:")])
    np.testing.assert_allclose(np.sort(means(out2 + ".summary"), 0),
                               np.sort(means(out + ".summary"), 0),
                               atol=0.05)
    # K mismatch is a clear error
    assert run_cli(["5", csv_file, str(tmp_path / "m3"), "5",
                    f"--init-from={out}.summary"]) == 1
    assert run_cli(["3", csv_file, str(tmp_path / "m4"), "3",
                    f"--init-from={tmp_path}/nope.summary"]) == 1


def test_cli_predict_from(csv_file, tmp_path):
    """Inference-only mode: .results under a saved model reproduce the fit
    run's memberships; error paths for bad model / dim mismatch."""
    out = str(tmp_path / "fit")
    assert run_cli(["3", csv_file, out, "3", "--min-iters=4", "--max-iters=4",
                    "--chunk-size=256"]) == 0
    pred = str(tmp_path / "pred")
    # the K positional is genuinely ignored (out-of-range placeholder is fine)
    rc = run_cli(["600", csv_file, pred, "--chunk-size=256",
                  f"--predict-from={out}.summary"])
    assert rc == 0
    fit_rows = (tmp_path / "fit.results").read_text().splitlines()
    pred_rows = (tmp_path / "pred.results").read_text().splitlines()
    assert len(pred_rows) == len(fit_rows)
    # 3-decimal model precision: argmax memberships must agree
    for a, b in zip(fit_rows, pred_rows):
        wa = np.argmax([float(v) for v in a.split("\t")[1].split(",")])
        wb = np.argmax([float(v) for v in b.split("\t")[1].split(",")])
        assert wa == wb
    # model echo written
    assert (tmp_path / "pred.summary").read_text().count("Cluster #") == 3
    # outfile colliding with the model: the echo must not clobber the model
    before = (tmp_path / "fit.summary").read_bytes()
    assert run_cli(["1", csv_file, str(tmp_path / "fit"),
                    f"--predict-from={out}.summary", "--chunk-size=256"]) == 0
    assert (tmp_path / "fit.summary").read_bytes() == before
    # missing model file
    assert run_cli(["1", csv_file, pred,
                    f"--predict-from={tmp_path}/nope.summary"]) == 1
    # dimension mismatch
    d2 = tmp_path / "d2.csv"
    d2.write_text("a,b\n1.0,2.0\n3.0,4.0\n")
    assert run_cli(["1", str(d2), pred,
                    f"--predict-from={out}.summary"]) == 1


def test_cli_bin_input(tmp_path, rng):
    data, _ = make_blobs(rng, n=300, d=2, k=2, dtype=np.float32)
    p = tmp_path / "events.bin"
    write_bin(str(p), data)
    rc = run_cli(["2", str(p), str(tmp_path / "o"), "2",
                  "--min-iters=2", "--max-iters=2", "--chunk-size=256"])
    assert rc == 0
    assert (tmp_path / "o.summary").exists()


def test_cli_mesh_byte_identical(csv_file, tmp_path):
    """A --mesh=8 run (sharded fit + sharded output pass over all 8 fake
    devices) produces byte-identical .summary/.results to the single-device
    run -- the within-host analog of the 2-process byte-identity test."""
    args = ["3", csv_file, None, "3", "--min-iters=3", "--max-iters=3",
            "--chunk-size=64", "--dtype=float64"]
    a1, a8 = list(args), list(args)
    a1[2] = str(tmp_path / "m1")
    a8[2] = str(tmp_path / "m8")
    a8.append("--mesh=8")
    assert run_cli(a1) == 0
    assert run_cli(a8) == 0
    assert ((tmp_path / "m8.summary").read_bytes()
            == (tmp_path / "m1.summary").read_bytes())
    with open(csv_file) as f:
        n_events = len(f.read().splitlines()) - 1  # minus header
    r1 = (tmp_path / "m1.results").read_bytes()
    assert r1.count(b"\n") == n_events
    assert (tmp_path / "m8.results").read_bytes() == r1


def test_cli_invalid_infile(tmp_path):
    rc = run_cli(["3", str(tmp_path / "missing.csv"), "out"])
    assert rc == 2  # gaussian.cu:1132


def test_cli_invalid_k(csv_file, tmp_path):
    assert run_cli(["0", csv_file, str(tmp_path / "o")]) == 1
    assert run_cli(["513", csv_file, str(tmp_path / "o")]) == 1  # > MAX_CLUSTERS


def test_cli_target_gt_k(csv_file, tmp_path):
    rc = run_cli(["3", csv_file, str(tmp_path / "o"), "5"])
    assert rc == 4  # gaussian.cu:1149-1153


def test_cli_no_output(csv_file, tmp_path):
    out = str(tmp_path / "noout")
    rc = run_cli(["2", csv_file, out, "2", "--no-output",
                  "--min-iters=2", "--max-iters=2", "--chunk-size=256"])
    assert rc == 0
    # summary file created but empty; no results file (ENABLE_OUTPUT=0
    # semantics, gaussian.cu:1015, 1042)
    assert (tmp_path / "noout.summary").read_text() == ""
    assert not (tmp_path / "noout.results").exists()


def test_cli_exit_74_on_torn_input(tmp_path, rng):
    """Unreadable/torn INPUT (a truncated BIN payload -- partial copy,
    crashed writer) maps to 74 (EX_IOERR), distinct from malformed
    content's reference exit 1 (docs/API.md exit-code table)."""
    data, _ = make_blobs(rng, n=200, d=3, k=2, dtype=np.float32)
    p = tmp_path / "torn.bin"
    write_bin(str(p), data)
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) // 2])  # header intact, payload torn
    assert run_cli(["2", str(p), str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2"]) == 74
    # malformed CONTENT (ragged rows) keeps the reference's exit 1
    bad = tmp_path / "ragged.csv"
    bad.write_text("a,b,c\n1,2,3\n4,5\n")
    assert run_cli(["2", str(bad), str(tmp_path / "o"), "2"]) == 1


def test_cli_exit_74_on_unreadable_checkpoints(csv_file, tmp_path):
    """When EVERY checkpoint step is unreadable, resume fails with
    CheckpointRestoreError -> exit 74 (EX_IOERR) instead of silently
    starting the sweep over."""
    ck = tmp_path / "ck"
    assert run_cli(["4", csv_file, str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2", "--chunk-size=256",
                    "--fused-sweep", f"--checkpoint-dir={ck}"]) == 0
    sweep = ck / "sweep"
    npzs = [f for f in sweep.iterdir() if f.suffix == ".npz"]
    assert npzs
    for f in npzs:  # tear every retained step
        f.write_bytes(b"not an npz")
    assert run_cli(["4", csv_file, str(tmp_path / "o2"), "2",
                    "--min-iters=2", "--max-iters=2", "--chunk-size=256",
                    "--fused-sweep", f"--checkpoint-dir={ck}"]) == 74


def test_cli_exit_75_on_preemption(csv_file, tmp_path):
    """A cooperative stop (here: the deterministic preempt injection
    standing in for SIGTERM) exits 75 (EX_TEMPFAIL) with the intra-K
    sub-step durable; the real-signal variant lives in
    tests/test_preemption.py."""
    from cuda_gmm_mpi_tpu.testing import faults

    ck = tmp_path / "ck"
    with faults.use({"preempt": {"iter": 2}}):
        rc = run_cli(["4", csv_file, str(tmp_path / "o"), "2",
                      "--min-iters=3", "--max-iters=3", "--chunk-size=256",
                      f"--checkpoint-dir={ck}"])
    assert rc == 75
    assert not (tmp_path / "o.summary").exists()
    assert [f for f in (ck / "sweep").iterdir() if ".iter" in f.name]
    # rerun (--resume auto default) completes from inside the fit
    assert run_cli(["4", csv_file, str(tmp_path / "o"), "2",
                    "--min-iters=3", "--max-iters=3", "--chunk-size=256",
                    f"--checkpoint-dir={ck}"]) == 0
    assert (tmp_path / "o.summary").exists()


def test_cli_allow_nonfinite_quarantines_rows(tmp_path):
    """--allow-nonfinite drops NaN/Inf rows at ingest (count-and-
    quarantine) instead of rejecting the file; the fit then runs on the
    clean remainder."""
    rows = ["a,b"] + [f"{x:.3f},{x + 1.0:.3f}" for x in
                      np.linspace(0.0, 9.0, 60)]
    rows[7] = "nan,3.0"
    rows[13] = "1e39,2.0"  # overflows compute float32: quarantined too
    p = tmp_path / "dirty.csv"
    p.write_text("\n".join(rows) + "\n")
    assert run_cli(["2", str(p), str(tmp_path / "o"), "2",
                    "--min-iters=2", "--max-iters=2"]) == 1
    assert run_cli(["2", str(p), str(tmp_path / "o"), "2", "--min-iters=2",
                    "--max-iters=2", "--allow-nonfinite"]) == 0
    # every SURVIVING event got memberships: 60 data rows - 2 quarantined
    results = (tmp_path / "o.results").read_text().splitlines()
    assert len(results) == 58


def test_cli_profile_and_trace_dir(csv_file, tmp_path, capsys):
    """--profile prints the 7-category report (gaussian.cu:967 analog) and
    --trace-dir captures a jax.profiler trace (SURVEY SS5.1's TPU-native
    tracing path), composed on one run."""
    out = str(tmp_path / "out")
    trace_dir = tmp_path / "traces"
    rc = run_cli(["2", csv_file, out, "2", "--profile",
                  f"--trace-dir={trace_dir}",
                  "--min-iters=2", "--max-iters=2", "--chunk-size=256"])
    assert rc == 0
    rep = capsys.readouterr().out
    assert "Phase profile" in rep
    for cat in ("e_step", "m_step", "constants", "reduce", "memcpy",
                "cpu", "mpi"):
        assert cat in rep
    # jax.profiler writes <dir>/plugins/profile/<ts>/*.xplane.pb
    captures = list(trace_dir.rglob("*.xplane.pb"))
    assert captures, f"no trace capture under {trace_dir}"
