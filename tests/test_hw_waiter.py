"""Unit tests for hw_wait_and_run.sh's detection predicates.

The waiter is the round's unattended tunnel-catcher; its `relay_alive`
port heuristic has been review-flagged twice (replace-vs-extend ignore
semantics; empty-line match inversion on a trailing separator). These
tests source the script with GMM_HW_SOURCE_ONLY=1 and drive the
predicates against a stubbed `ss` on PATH, so the shell logic is pinned
without any real sockets or a live relay.
"""

import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "hw_wait_and_run.sh")


def run_relay_alive(tmp_path, listen_ports, env_extra=None):
    """rc of relay_alive() with `ss -tln` stubbed to the given ports."""
    stub = tmp_path / "ss"
    lines = ["State  Recv-Q Send-Q Local Address:Port Peer Address:Port"]
    lines += [f"LISTEN 0      128    0.0.0.0:{p}      0.0.0.0:*"
              for p in listen_ports]
    stub.write_text("#!/bin/sh\n" + "\n".join(
        f"echo '{ln}'" for ln in lines) + "\n")
    stub.chmod(0o755)
    env = dict(os.environ)
    env.pop("GMM_HW_RELAY_PORTS", None)
    env.pop("GMM_HW_IGNORE_PORTS", None)
    env.update(env_extra or {})
    env["GMM_HW_SOURCE_ONLY"] = "1"
    env["PATH"] = f"{tmp_path}:{env['PATH']}"
    r = subprocess.run(
        ["bash", "-c", f". '{SCRIPT}'; relay_alive"],
        capture_output=True, text=True, env=env, timeout=30)
    return r.returncode


def test_baseline_ports_are_not_a_relay(tmp_path):
    assert run_relay_alive(tmp_path, [48271, 2024]) != 0


def test_extra_port_means_alive(tmp_path):
    assert run_relay_alive(tmp_path, [48271, 2024, 35975]) == 0


def test_no_ports_at_all_is_dead(tmp_path):
    assert run_relay_alive(tmp_path, []) != 0


def test_ignore_ports_extend_not_replace(tmp_path):
    """A user-supplied ignore list must EXTEND the baseline: with jupyter's
    8888 ignored, the baseline listeners alone still must not read as a
    live relay (the replace semantics bug would return alive here)."""
    env = {"GMM_HW_IGNORE_PORTS": "8888"}
    assert run_relay_alive(tmp_path, [48271, 2024, 8888], env) != 0
    assert run_relay_alive(tmp_path, [48271, 2024, 8888, 35975], env) == 0
    # Comma-separated lists must ignore EVERY listed port (verbatim
    # interpolation made '8888,9999' one impossible pattern that ignored
    # nothing, so two dev servers read as a live relay).
    env = {"GMM_HW_IGNORE_PORTS": "8888,9999"}
    assert run_relay_alive(tmp_path, [48271, 2024, 8888, 9999], env) != 0
    assert run_relay_alive(tmp_path, [48271, 2024, 8888, 9999, 35975],
                           env) == 0


def test_explicit_relay_ports_match_only_those(tmp_path):
    env = {"GMM_HW_RELAY_PORTS": "8471,8472"}
    # an unrelated extra listener is NOT the relay
    assert run_relay_alive(tmp_path, [48271, 2024, 9999], env) != 0
    # one of the named ports is
    assert run_relay_alive(tmp_path, [48271, 2024, 8472], env) == 0


def test_trailing_separator_cannot_invert_the_check(tmp_path):
    """'8471|' (or ',') must not match the empty string and report a dead
    relay as alive."""
    for sep_val in ("8471|", "8471,"):
        env = {"GMM_HW_RELAY_PORTS": sep_val}
        assert run_relay_alive(tmp_path, [48271, 2024], env) != 0


def run_machine_quiet(tmp_path, ps_lines):
    """rc of machine_quiet() with `ps -eo args` stubbed."""
    stub = tmp_path / "ps"
    stub.write_text("#!/bin/sh\n" + "\n".join(
        f"echo '{ln}'" for ln in (["ARGS"] + ps_lines)) + "\n")
    stub.chmod(0o755)
    env = dict(os.environ)
    env["GMM_HW_SOURCE_ONLY"] = "1"
    env["PATH"] = f"{tmp_path}:{env['PATH']}"
    r = subprocess.run(
        ["bash", "-c", f". '{SCRIPT}'; machine_quiet"],
        capture_output=True, text=True, env=env, timeout=30)
    return r.returncode


def test_machine_quiet_detects_bench_and_pytest(tmp_path):
    assert run_machine_quiet(tmp_path, ["/bin/bash", "python bench.py"]) != 0
    assert run_machine_quiet(
        tmp_path, ["python -m pytest tests/ -x -q"]) != 0
    assert run_machine_quiet(tmp_path, ["/bin/bash", "vim notes.md"]) == 0


def test_machine_quiet_ignores_the_driver_wrapper(tmp_path):
    """The build driver's own command line QUOTES 'pytest'/'bench.py' (its
    system prompt mentions them); lines containing 'claude' are filtered
    before matching so the driver does not read as a busy machine."""
    driver = "claude -p --append-system-prompt 'run pytest and bench.py'"
    assert run_machine_quiet(tmp_path, [driver]) == 0
    assert run_machine_quiet(tmp_path, [driver, "python bench.py"]) != 0


def test_executed_with_source_only_is_a_noop(tmp_path):
    """GMM_HW_SOURCE_ONLY leaked into an EXECUTED run must not fall through
    into the hours-long wait loop."""
    env = dict(os.environ)
    env["GMM_HW_SOURCE_ONLY"] = "1"
    r = subprocess.run(["bash", SCRIPT], capture_output=True, text=True,
                       env=env, timeout=30)
    assert r.returncode == 0
    assert "hw_wait" not in r.stdout
