"""Weighted events: integer sample_weight == replicated rows, exactly.

The fused E+M pass multiplies responsibilities and log-evidence by the
per-event weight row, so every sufficient statistic (loglik, Nk, M1, M2) of
a weight-w event equals w copies of it -- the whole EM trajectory must
match a fit on the physically replicated dataset (same init pinned via
init_means so seeding differences can't leak in).
"""

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GaussianMixture, GMMConfig
from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
from cuda_gmm_mpi_tpu.validation import InvalidInputError

from .conftest import make_blobs


@pytest.mark.parametrize("cov_type", ["full", "diag"])
def test_integer_weights_equal_replication(rng, cov_type):
    k, d, n = 3, 3, 500
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float64)
    w = rng.integers(0, 4, size=n).astype(np.float64)
    replicated = np.repeat(data, w.astype(int), axis=0)

    kw = dict(min_iters=6, max_iters=6, chunk_size=128, dtype="float64",
              covariance_type=cov_type, center_data=False,
              covariance_dynamic_range=1e30)  # avgvar ~ 0: it is seeded
    # from the UNWEIGHTED data variance, which replication shifts -- not
    # part of the weighting semantics under test
    gw = GaussianMixture(k, target_components=k, means_init=centers,
                         **kw).fit(data, sample_weight=w)
    gr = GaussianMixture(k, target_components=k, means_init=centers,
                         **kw).fit(replicated)

    np.testing.assert_allclose(gw.weights_, gr.weights_, rtol=1e-10)
    np.testing.assert_allclose(gw.means_, gr.means_, rtol=1e-9, atol=1e-10)
    np.testing.assert_allclose(gw.covariances_, gr.covariances_,
                               rtol=1e-8, atol=1e-10)


def test_weighted_loglik_matches_replication(rng):
    data, _ = make_blobs(rng, n=300, d=2, k=2, dtype=np.float64)
    w = rng.integers(1, 3, size=len(data)).astype(np.float64)
    cfg = dict(min_iters=3, max_iters=3, chunk_size=64, dtype="float64",
               center_data=False, covariance_dynamic_range=1e30)
    centers = data[:2]
    rw = fit_gmm(data, 2, 2, GMMConfig(**cfg), init_means=centers,
                 sample_weight=w)
    rr = fit_gmm(np.repeat(data, w.astype(int), axis=0), 2, 2,
                 GMMConfig(**cfg), init_means=centers)
    np.testing.assert_allclose(rw.final_loglik, rr.final_loglik, rtol=1e-10)


def test_sample_weight_validation(rng):
    data, _ = make_blobs(rng, n=100, d=2, k=2, dtype=np.float64)
    cfg = GMMConfig(min_iters=1, max_iters=1, chunk_size=64, dtype="float64")
    with pytest.raises(ValueError, match="sample_weight must be"):
        fit_gmm(data, 2, 2, cfg, sample_weight=np.ones(7))
    with pytest.raises(InvalidInputError, match="nonnegative"):
        fit_gmm(data, 2, 2, cfg,
                sample_weight=np.full(len(data), -1.0))
    bad = np.ones(len(data))
    bad[3] = np.nan
    with pytest.raises(InvalidInputError, match="finite"):
        fit_gmm(data, 2, 2, cfg, sample_weight=bad)
    # normalized-probability weights (sum ~ 1) would make every cluster
    # look empty under the absolute Nk thresholds: rejected with guidance
    with pytest.raises(InvalidInputError, match="multiplicities"):
        fit_gmm(data, 2, 2, cfg,
                sample_weight=np.full(len(data), 1.0 / len(data)))


def test_weighted_fused_sweep_matches_host(rng):
    """sample_weight rides the same wts arrays into the fused on-device
    sweep; trajectories match the host-driven sweep exactly."""
    data, _ = make_blobs(rng, n=400, d=2, k=3, dtype=np.float64)
    w = rng.integers(1, 3, size=len(data)).astype(np.float64)
    kw = dict(min_iters=3, max_iters=3, chunk_size=128, dtype="float64")
    rh = fit_gmm(data, 5, 2, GMMConfig(**kw), sample_weight=w)
    rf = fit_gmm(data, 5, 2, GMMConfig(fused_sweep=True, **kw),
                 sample_weight=w)
    assert rf.ideal_num_clusters == rh.ideal_num_clusters
    np.testing.assert_allclose(rf.final_loglik, rh.final_loglik, rtol=1e-12)
    np.testing.assert_allclose(rf.means, rh.means, rtol=1e-10)


def test_fractional_weights_scale_statistics(rng):
    """Non-integer weights: halving every weight must leave the MLE fixed
    point unchanged (weights enter every statistic homogeneously; only pi's
    normalizer and the loglik scale)."""
    data, _ = make_blobs(rng, n=400, d=2, k=2, dtype=np.float64)
    centers = data[:2]
    kw = dict(min_iters=5, max_iters=5, chunk_size=128, dtype="float64",
              center_data=False, covariance_dynamic_range=1e30)
    g1 = GaussianMixture(2, target_components=2, means_init=centers,
                         **kw).fit(data, sample_weight=np.ones(len(data)))
    gh = GaussianMixture(2, target_components=2, means_init=centers,
                         **kw).fit(data,
                                   sample_weight=np.full(len(data), 0.5))
    np.testing.assert_allclose(gh.means_, g1.means_, rtol=1e-9)
    np.testing.assert_allclose(gh.weights_, g1.weights_, rtol=1e-9)
