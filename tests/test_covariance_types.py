"""Covariance families beyond the reference's full/DIAG_ONLY pair.

'spherical' (sigma^2 I per cluster) and 'tied' (one shared D x D covariance)
are capability upgrades; these tests pin their M-step semantics against
NumPy-computed MLE formulas, their structural invariants end-to-end, and
(for tied, whose pooling crosses the cluster mesh axis) sharded-vs-plain
parity.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GaussianMixture, GMMConfig
from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
from cuda_gmm_mpi_tpu.ops.formulas import n_free_params
from cuda_gmm_mpi_tpu.ops.mstep import apply_mstep, chunk_stats

from .conftest import make_blobs
from .test_estep import make_state


def test_config_coupling():
    assert GMMConfig(diag_only=True).covariance_type == "diag"
    assert GMMConfig(covariance_type="diag").diag_only is True
    assert GMMConfig(covariance_type="spherical").diag_only is True
    assert GMMConfig(covariance_type="tied").diag_only is False
    with pytest.raises(ValueError, match="tied"):
        GMMConfig(covariance_type="tied", diag_only=True)
    with pytest.raises(ValueError, match="covariance_type"):
        GMMConfig(covariance_type="oblong")


def test_spherical_mstep_is_mean_of_diag_variances(rng):
    k, d, n = 4, 5, 400
    state = make_state(rng, k, d)
    x = rng.normal(scale=2.0, size=(n, d))
    stats = chunk_stats(state, jnp.asarray(x), diag_only=True)
    s_diag = apply_mstep(state, stats, diag_only=True)
    s_sph = apply_mstep(state, stats, diag_only=True,
                        covariance_type="spherical")
    var_diag = np.diagonal(np.asarray(s_diag.R), axis1=1, axis2=2)
    var_sph = np.diagonal(np.asarray(s_sph.R), axis1=1, axis2=2)
    # sigma^2_k = mean_d var_kd, identical across dims
    np.testing.assert_allclose(
        var_sph,
        np.broadcast_to(var_diag.mean(axis=1, keepdims=True), var_sph.shape),
        rtol=1e-12)
    assert np.ptp(var_sph, axis=1).max() == 0.0
    # means unaffected by the covariance constraint
    np.testing.assert_array_equal(np.asarray(s_sph.means),
                                  np.asarray(s_diag.means))


def test_tied_mstep_is_pooled_full_covariance(rng):
    k, d, n = 3, 4, 500
    state = make_state(rng, k, d)
    x = rng.normal(scale=2.0, size=(n, d))
    stats = chunk_stats(state, jnp.asarray(x))
    s_tied = apply_mstep(state, stats, covariance_type="tied")
    R = np.asarray(s_tied.R)
    # every cluster shares one covariance
    for c in range(1, k):
        np.testing.assert_array_equal(R[c], R[0])
    # and it equals the NumPy pooled MLE with one avgvar loading
    Nk = np.asarray(stats.Nk)
    mu = np.asarray(stats.M1) / Nk[:, None]
    scatter = (np.asarray(stats.M2)
               - Nk[:, None, None] * mu[:, :, None] * mu[:, None, :])
    avg = float(np.asarray(state.avgvar)[0])
    expect = (scatter.sum(0) + avg * np.eye(d)) / Nk.sum()
    np.testing.assert_allclose(R[0], expect, rtol=1e-10, atol=1e-12)


def test_tied_degenerate_guards(rng):
    """Dead-zone clusters (0.5 < Nk < 1) neither scatter nor count, and an
    all-empty pool falls back to the identity (the tied analog of
    gaussian.cu:669-678)."""
    k, d, n = 3, 4, 300
    state = make_state(rng, k, d)
    x = rng.normal(scale=2.0, size=(n, d))
    stats = chunk_stats(state, jnp.asarray(x))
    # Force cluster 2 into the dead zone: its scatter is zeroed by the
    # Nk >= 1 guard, so the pooled count must exclude its Nk too.
    import dataclasses
    Nk = np.asarray(stats.Nk).copy()
    Nk[2] = 0.7
    stats_dz = dataclasses.replace(stats, Nk=jnp.asarray(Nk))
    s = apply_mstep(state, stats_dz, covariance_type="tied")
    Nk_live = Nk[:2]
    mu = np.asarray(stats.M1)[:2] / Nk_live[:, None]
    scatter = (np.asarray(stats.M2)[:2]
               - Nk_live[:, None, None] * mu[:, :, None] * mu[:, None, :])
    avg = float(np.asarray(state.avgvar)[0])
    expect = (scatter.sum(0) + avg * np.eye(d)) / Nk_live.sum()
    np.testing.assert_allclose(np.asarray(s.R)[0], expect,
                               rtol=1e-10, atol=1e-12)
    # All clusters empty -> identity shared covariance, not avgvar/1e-30.
    stats_empty = dataclasses.replace(
        stats, Nk=jnp.zeros_like(stats.Nk))
    s0 = apply_mstep(state, stats_empty, covariance_type="tied")
    np.testing.assert_array_equal(np.asarray(s0.R)[0], np.eye(d))


@pytest.mark.parametrize("ct", ["spherical", "tied"])
def test_fit_end_to_end(rng, ct):
    centers = rng.normal(scale=8.0, size=(3, 3))
    labels = rng.integers(0, 3, size=1200)
    data = centers[labels] + rng.normal(size=(1200, 3))
    gm = GaussianMixture(3, target_components=3, covariance_type=ct,
                         min_iters=15, max_iters=15, chunk_size=256,
                         dtype="float64").fit(data)
    cov = gm.covariances_
    if ct == "spherical":
        for c in range(3):
            diag = np.diag(cov[c])
            assert np.ptp(diag) == 0.0
            np.testing.assert_array_equal(cov[c], np.diag(diag))
    else:
        for c in range(1, 3):
            np.testing.assert_array_equal(cov[c], cov[0])
    # blob recovery still works under the constrained families
    pred = gm.predict(data)
    agree = sum(
        np.bincount(pred[labels == c]).max() for c in range(3)
    )
    assert agree / len(labels) > 0.95
    assert np.isfinite(gm.loglik_)


def test_monotone_loglik_under_constraints(rng):
    """EM's monotonicity guarantee holds for the constrained M-steps too
    (both are exact MLEs of their family given the responsibilities)."""
    data, _ = make_blobs(rng, n=800, d=3, k=3, dtype=np.float64)
    for ct in ("spherical", "tied"):
        lls = []
        for iters in (2, 6, 12):
            r = fit_gmm(data, 3, 3,
                        GMMConfig(covariance_type=ct, min_iters=iters,
                                  max_iters=iters, chunk_size=256,
                                  dtype="float64"))
            lls.append(r.final_loglik)
        assert lls[0] <= lls[1] + 1e-9 <= lls[2] + 2e-9, (ct, lls)


def test_tied_sharded_matches_plain(rng):
    """Tied pooling crosses the cluster mesh axis via psum: a (2, 2) mesh fit
    must reproduce the single-device tied fit."""
    data, _ = make_blobs(rng, n=640, d=3, k=4, dtype=np.float64)
    kw = dict(covariance_type="tied", min_iters=5, max_iters=5,
              chunk_size=64, dtype="float64")
    r_plain = fit_gmm(data, 4, 4, GMMConfig(**kw))
    r_shard = fit_gmm(data, 4, 4, GMMConfig(mesh_shape=(2, 2), **kw))
    np.testing.assert_allclose(r_shard.final_loglik, r_plain.final_loglik,
                               rtol=1e-9)
    np.testing.assert_allclose(np.sort(r_shard.means, 0),
                               np.sort(r_plain.means, 0),
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(r_shard.covariances, r_plain.covariances,
                               rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("ct", ["spherical", "tied"])
def test_fused_sweep_matches_host_sweep(rng, ct):
    """covariance_type reaches the fused whole-sweep-on-device path too."""
    data, _ = make_blobs(rng, n=600, d=3, k=3, dtype=np.float64)
    kw = dict(covariance_type=ct, min_iters=4, max_iters=4, chunk_size=128,
              dtype="float64")
    r_host = fit_gmm(data, 5, 2, GMMConfig(**kw))
    r_fused = fit_gmm(data, 5, 2, GMMConfig(fused_sweep=True, **kw))
    assert r_fused.ideal_num_clusters == r_host.ideal_num_clusters
    np.testing.assert_allclose(r_fused.final_loglik, r_host.final_loglik,
                               rtol=1e-12)
    np.testing.assert_allclose(r_fused.covariances, r_host.covariances,
                               rtol=1e-10, atol=1e-12)


def test_tied_fused_sweep_on_mesh_matches_plain(rng):
    """The deepest composition: tied's cross-cluster psum inside the fused
    whole-sweep-on-device program under a (2, 2) shard_map mesh."""
    data, _ = make_blobs(rng, n=640, d=3, k=4, dtype=np.float64)
    kw = dict(covariance_type="tied", min_iters=3, max_iters=3,
              chunk_size=64, dtype="float64")
    r_plain = fit_gmm(data, 4, 2, GMMConfig(**kw))
    r_mesh = fit_gmm(data, 4, 2, GMMConfig(mesh_shape=(2, 2),
                                           fused_sweep=True, **kw))
    assert r_mesh.ideal_num_clusters == r_plain.ideal_num_clusters
    np.testing.assert_allclose(r_mesh.final_loglik, r_plain.final_loglik,
                               rtol=1e-9)
    np.testing.assert_allclose(r_mesh.covariances, r_plain.covariances,
                               rtol=1e-8, atol=1e-10)


def test_n_free_params_by_family():
    k, d = 5, 4
    full = k * (1 + d + d * (d + 1) / 2) - 1
    assert n_free_params(k, d) == full
    assert n_free_params(k, d, covariance_type="diag") == k * (1 + 2 * d) - 1
    assert n_free_params(k, d, covariance_type="spherical") == k * (2 + d) - 1
    assert n_free_params(k, d, covariance_type="tied") == (
        k * (1 + d) + d * (d + 1) / 2 - 1
    )
    # legacy kwarg still works
    assert n_free_params(k, d, diag_only=True) == k * (1 + 2 * d) - 1
