"""``gmm diff`` / ``gmm runs`` cross-run regression analytics (round 15).

Contracts under test (telemetry/diff.py, docs/API.md exit codes):

  * two back-to-back same-config runs diff CLEAN (exit 0) -- the
    default gates are count-shaped precisely so wall jitter can't trip
    them;
  * an injected slowdown (read_slow fault on the pipelined ingest path)
    trips a --fail-on gate, NAMES the regressed metric, and exits 1 --
    the CI contract;
  * the --fail-on spec grammar: relative (``>N%``), absolute (``>N``),
    and lower-is-worse (``<``) directions, zero-baseline semantics, and
    bad specs / unreadable targets exiting 2;
  * ``gmm runs DIR`` indexes historical streams (run id, fingerprint,
    backend, wall, health) and exits 2 on a non-directory;
  * ``gmm report --json`` emits the same rollup shape diff consumes.
"""

import json
import pathlib

import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm
from cuda_gmm_mpi_tpu.cli import main as cli_main
from cuda_gmm_mpi_tpu.io import FileSource, write_bin
from cuda_gmm_mpi_tpu.telemetry import read_stream
from cuda_gmm_mpi_tpu.telemetry.diff import (FailSpec, diff_main, runs_main,
                                             summarize_run)
from cuda_gmm_mpi_tpu.telemetry.report import report_main
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import make_blobs


@pytest.fixture(scope="module")
def two_streams(tmp_path_factory):
    """Two fits of the same data under the same config, two streams.

    Module-scoped: five tests consume the identical pair read-only, so
    the four EM fits (and their jit compiles) run once per session.
    Tests that need EXTRA streams must write them to their own tmp_path,
    never into this directory (the `gmm runs` test indexes it)."""
    gen = np.random.default_rng(1234)
    data, _ = make_blobs(gen, n=400, d=3, k=3, dtype=np.float32)
    base = tmp_path_factory.mktemp("two_streams")
    paths = []
    for name in ("a", "b"):
        path = str(base / f"{name}.jsonl")
        cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=128, seed=0,
                        metrics_file=path)
        fit_gmm(data, 3, 3, cfg)
        paths.append(path)
    return paths


def test_diff_identical_runs_clean(two_streams, capsys):
    """The CI baseline: same config, same data -> exit 0 through the
    real CLI dispatch, with the shared-metric table rendered."""
    a, b = two_streams
    assert cli_main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "clean: no regressions" in out
    assert "REGRESSION" not in out
    # same config -> same fingerprint -> no mismatch note
    assert "fingerprints differ" not in out


def test_diff_injected_ingest_regression_names_metric(tmp_path, rng,
                                                      capsys):
    """A read_slow fault on run B's pipelined ingestion shifts the
    prefetch wait; the --fail-on gate trips, names the metric, exits 1."""
    n, chunk = 1024, 128
    data, _ = make_blobs(rng, n=n, d=3, k=3, dtype=np.float32)
    bin_path = str(tmp_path / "events.bin")
    write_bin(bin_path, data)
    kw = dict(min_iters=2, max_iters=2, chunk_size=chunk, seed=0,
              stream_events=True, ingest="pipelined")

    a = str(tmp_path / "a.jsonl")
    fit_gmm(FileSource(bin_path), 3, 3,
            config=GMMConfig(metrics_file=a, **kw))
    b = str(tmp_path / "b.jsonl")
    with faults.use({"read_slow": {"ms": 50, "block": 1, "times": 3}}):
        fit_gmm(FileSource(bin_path), 3, 3,
                config=GMMConfig(metrics_file=b, **kw))

    waits = [summarize_run(read_stream(p))["metrics"].get(
        "ingest.prefetch_wait_s", 0.0) for p in (a, b)]
    assert waits[1] > waits[0]  # the fault really moved the metric

    spec = "ingest.prefetch_wait_s>0.05"
    assert cli_main(["diff", a, b, "--fail-on", spec]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION ingest.prefetch_wait_s" in out
    assert "1 regression(s)" in out
    # ...and the unfaulted pair still diffs clean under the same gate
    assert diff_main([a, a, "--fail-on", spec]) == 0


def test_fail_spec_grammar():
    rel = FailSpec("wall_s>15%")
    assert rel.relative and rel.op == ">" and rel.threshold == 15.0
    assert rel.check(100.0, 110.0) is None           # +10% <= 15%
    assert "wall_s" in rel.check(100.0, 120.0)       # +20% trips
    assert rel.check(None, 120.0) is None            # not comparable
    assert rel.check(0.0, 0.0) is None               # zero baseline, clean
    assert rel.check(0.0, 5.0) is not None           # from-zero regression

    lower = FailSpec("iters_per_s<10%")
    assert lower.check(100.0, 95.0) is None          # -5% ok
    assert "iters_per_s" in lower.check(100.0, 80.0)  # -20% trips

    absolute = FailSpec("serve.p99_ms>5")
    assert not absolute.relative
    assert absolute.check(10.0, 14.0) is None        # +4 <= 5
    assert "serve.p99_ms" in absolute.check(10.0, 16.0)

    for bad in ("wall_s", ">5", "wall_s>", "wall_s>abc", ""):
        with pytest.raises(ValueError):
            FailSpec(bad)


def test_diff_usage_errors_exit_2(two_streams, tmp_path, capsys):
    a, b = two_streams
    assert diff_main([a, str(tmp_path / "missing.jsonl")]) == 2
    assert diff_main([a, b, "--fail-on", "bogus-spec"]) == 2
    capsys.readouterr()


def test_diff_json_and_custom_gate(two_streams, tmp_path, rng, capsys):
    """--json emits the machine contract: both rollups, the gate list,
    and the named regressions; a total_iters>0 absolute gate on unequal
    runs trips it."""
    a, b = two_streams
    assert cli_main(["diff", a, b, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["clean"] is True and doc["regressions"] == []
    assert doc["a"]["metrics"]["total_iters"] \
        == doc["b"]["metrics"]["total_iters"]
    assert doc["a"]["fingerprint"] == doc["b"]["fingerprint"]
    assert any(s.startswith("compiles>") for s in doc["fail_on"])

    # a third run with MORE iterations and a different chunking: the
    # custom absolute gate names the iteration growth
    data, _ = make_blobs(rng, n=400, d=3, k=3, dtype=np.float32)
    c = str(tmp_path / "c.jsonl")
    fit_gmm(data, 3, 3, GMMConfig(min_iters=4, max_iters=4,
                                  chunk_size=64, seed=0, metrics_file=c))
    rc = diff_main([a, c, "--json", "--no-default-gates",
                    "--fail-on", "total_iters>0"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["clean"] is False
    assert any("total_iters" in r for r in doc["regressions"])
    # chunk_size is a config-identity field -> fingerprints differ ->
    # the comparison renders with a loud note instead of failing
    assert any("fingerprints differ" in n_ for n_ in doc["notes"])


def test_summarize_run_id_without_run_start():
    """serve-only and run_summary-only streams still report their run_id
    (regression: setdefault on the pre-seeded None key was a no-op, so
    `gmm diff`/`gmm runs` showed '?' for every headless stream)."""
    serve = summarize_run([{"event": "serve_summary", "run_id": "abc123",
                            "requests": 4, "wall_s": 1.0}])
    assert serve["run_id"] == "abc123"
    summary = summarize_run([{"event": "run_summary", "run_id": "def456",
                              "wall_s": 2.0, "total_iters": 3}])
    assert summary["run_id"] == "def456"


def test_runs_indexes_stream_directory(two_streams, tmp_path, capsys):
    stream_dir = str(pathlib.Path(two_streams[0]).parent)
    assert cli_main(["runs", stream_dir]) == 0
    out = capsys.readouterr().out
    assert "a.jsonl" in out and "b.jsonl" in out
    assert "ok" in out  # clean health column

    assert runs_main([stream_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["runs"]) == 2
    row = doc["runs"][0]
    assert row["run_id"] and row["fingerprint"] and row["backend"]
    assert row["wall_s"] > 0 and row["health"] == "ok"
    # both rows carry the same config fingerprint
    assert len({r["fingerprint"] for r in doc["runs"]}) == 1

    assert runs_main([str(tmp_path / "nope")]) == 2
    capsys.readouterr()


def test_report_json_is_the_diff_rollup(two_streams, capsys):
    """`gmm report --json` and summarize_run are the SAME shape -- one
    rollup for humans' diffs and scripts alike."""
    a, _ = two_streams
    assert report_main(["--json", a]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == json.loads(json.dumps(summarize_run(read_stream(a)),
                                        sort_keys=True))
    m = doc["metrics"]
    assert m["wall_s"] > 0 and m["total_iters"] > 0
    assert m["compiles"] >= 1  # the v2.2 profile fold rode along
    assert doc["kind"] == "stream" and doc["fingerprint"]
