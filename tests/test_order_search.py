"""End-to-end fit_gmm: model-order search, best-model save, memberships."""

import numpy as np

from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import compute_memberships, fit_gmm

from .conftest import make_blobs


def fast_cfg(**kw):
    base = dict(min_iters=4, max_iters=4, chunk_size=512, dtype="float64")
    base.update(kw)
    return GMMConfig(**base)


def test_target_k_fit(rng):
    data, centers = make_blobs(rng, n=1200, d=3, k=4)
    cfg = fast_cfg(min_iters=15, max_iters=15)
    result = fit_gmm(data, 8, 4, config=cfg)
    assert result.ideal_num_clusters == 4
    # recovered means close to true centers (well-separated blobs)
    got = sorted(map(tuple, np.round(result.means, 0)))
    exp = sorted(map(tuple, np.round(centers, 0)))
    err = np.abs(np.array(got) - np.array(exp)).max()
    assert err <= 1.5
    # sweep visited K = 8,7,6,5,4
    assert [rec[0] for rec in result.sweep_log] == [8, 7, 6, 5, 4]


def test_search_down_to_one(rng):
    data, _ = make_blobs(rng, n=600, d=2, k=3)
    result = fit_gmm(data, 5, 0, config=fast_cfg())
    ks = [rec[0] for rec in result.sweep_log]
    assert ks[0] == 5 and ks[-1] == 1
    # best rissanen selected
    assert result.min_rissanen == min(rec[2] for rec in result.sweep_log)


def test_criterion_bic_aic_selection(rng):
    """--criterion=bic/aic: scores match the closed forms, the best one is
    selected, and the fused sweep agrees with the host sweep."""
    import math

    from cuda_gmm_mpi_tpu.ops.formulas import model_score

    data, _ = make_blobs(rng, n=900, d=2, k=3)
    n = len(data)
    for crit in ("bic", "aic"):
        r = fit_gmm(data, 5, 0, config=fast_cfg(criterion=crit))
        # every sweep row's score is the criterion's closed form
        for k, ll, score, _, _ in r.sweep_log:
            expect = model_score(ll, int(k), n, 2, crit)
            assert math.isclose(score, expect, rel_tol=1e-12), (crit, k)
        assert r.min_rissanen == min(rec[2] for rec in r.sweep_log)
        # BIC/AIC still find the true K on separated blobs
        assert r.ideal_num_clusters == 3
        # fused whole-sweep-on-device path scores identically
        rf = fit_gmm(data, 5, 0,
                     config=fast_cfg(criterion=crit, fused_sweep=True))
        assert rf.ideal_num_clusters == r.ideal_num_clusters
        np.testing.assert_allclose(rf.min_rissanen, r.min_rissanen,
                                   rtol=1e-12)


def test_checkpoint_criterion_mismatch_starts_fresh(rng, tmp_path):
    """A checkpoint saved under one criterion must not be resumed under
    another (the scores live on different scales)."""
    data, _ = make_blobs(rng, n=400, d=2, k=2)
    ck = str(tmp_path / "ck")
    fit_gmm(data, 4, 2, config=fast_cfg(checkpoint_dir=ck))
    # same dir, different criterion: fresh sweep, result identical to a
    # checkpoint-free bic fit
    r_resumed = fit_gmm(data, 4, 2, config=fast_cfg(checkpoint_dir=ck,
                                                    criterion="bic"))
    r_clean = fit_gmm(data, 4, 2, config=fast_cfg(criterion="bic"))
    assert r_resumed.ideal_num_clusters == r_clean.ideal_num_clusters
    np.testing.assert_allclose(r_resumed.min_rissanen, r_clean.min_rissanen,
                               rtol=1e-12)
    assert len(r_resumed.sweep_log) == len(r_clean.sweep_log)


def test_checkpoint_covariance_mismatch_starts_fresh(rng, tmp_path):
    """Same guard for the covariance family: a tied run must not continue a
    full-covariance run's checkpoint."""
    data, _ = make_blobs(rng, n=400, d=2, k=2)
    ck = str(tmp_path / "ck")
    fit_gmm(data, 4, 2, config=fast_cfg(checkpoint_dir=ck))
    r_resumed = fit_gmm(data, 4, 2, config=fast_cfg(
        checkpoint_dir=ck, covariance_type="tied"))
    r_clean = fit_gmm(data, 4, 2, config=fast_cfg(covariance_type="tied"))
    np.testing.assert_allclose(r_resumed.min_rissanen, r_clean.min_rissanen,
                               rtol=1e-12)
    np.testing.assert_allclose(r_resumed.covariances, r_clean.covariances,
                               rtol=1e-10)
    assert len(r_resumed.sweep_log) == len(r_clean.sweep_log)


def test_memberships_shape_and_normalization(rng):
    data, _ = make_blobs(rng, n=500, d=3, k=3)
    cfg = fast_cfg()
    result = fit_gmm(data, 3, 3, config=cfg)
    w = compute_memberships(result, data, cfg)
    assert w.shape == (data.shape[0], 3)
    np.testing.assert_allclose(w.sum(1), 1.0, rtol=1e-8)


def test_centering_invariance(rng):
    """fit with centering == fit without (means shifted back exactly)."""
    data, _ = make_blobs(rng, n=400, d=2, k=2)
    data = data + 500.0  # big offset
    r1 = fit_gmm(data, 3, 2, config=fast_cfg(center_data=True))
    r2 = fit_gmm(data, 3, 2, config=fast_cfg(center_data=False))
    np.testing.assert_allclose(r1.means, r2.means, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(r1.state.R), np.asarray(r2.state.R), rtol=1e-5, atol=1e-6
    )


def test_single_cluster(rng):
    data, _ = make_blobs(rng, n=300, d=2, k=2)
    result = fit_gmm(data, 1, 1, config=fast_cfg())
    assert result.ideal_num_clusters == 1
    np.testing.assert_allclose(result.means[0], data.mean(0), rtol=1e-5)


def test_n_init_restarts_pick_best(rng):
    """n_init restarts never do worse than any single init they contain,
    and fix the local-optimum miss the single deterministic init can hit."""
    from .conftest import make_blobs

    data, _ = make_blobs(rng, n=900, d=3, k=4)
    kw = dict(min_iters=8, max_iters=8, chunk_size=256, dtype="float64")
    singles = [
        fit_gmm(data, 4, 4, config=GMMConfig(
            seed_method="kmeans++", seed=s, **kw))
        for s in range(3)
    ]
    multi = fit_gmm(data, 4, 4, config=GMMConfig(n_init=3, seed=0, **kw))
    assert multi.min_rissanen <= min(s.min_rissanen for s in singles) + 1e-9
    # deterministic: same seeds -> same pick
    multi2 = fit_gmm(data, 4, 4, config=GMMConfig(n_init=3, seed=0, **kw))
    np.testing.assert_allclose(multi2.min_rissanen, multi.min_rissanen,
                               rtol=1e-12)


def test_n_init_with_fused_sweep(rng):
    from .conftest import make_blobs

    data, _ = make_blobs(rng, n=600, d=3, k=3)
    kw = dict(min_iters=5, max_iters=5, chunk_size=256, dtype="float64")
    r1 = fit_gmm(data, 5, 3, config=GMMConfig(n_init=2, **kw))
    r2 = fit_gmm(data, 5, 3, config=GMMConfig(n_init=2, fused_sweep=True, **kw))
    np.testing.assert_allclose(r2.min_rissanen, r1.min_rissanen, rtol=1e-10)
    assert r2.ideal_num_clusters == r1.ideal_num_clusters


def test_result_pickles_without_model(rng, tmp_path):
    """GMMResult serializes (the carried fitted model holds process-bound
    jitted executables and is dropped); a restored result still produces
    memberships via the per-config fallback model."""
    import pickle

    from cuda_gmm_mpi_tpu.models.order_search import compute_memberships

    data, _ = make_blobs(rng, n=256, d=3, k=2)
    cfg = GMMConfig(min_iters=2, max_iters=2, chunk_size=128, dtype="float64")
    r = fit_gmm(data, 2, 2, config=cfg)
    assert r.model is not None
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.model is None
    w1 = compute_memberships(r, data, cfg)
    w2 = compute_memberships(r2, data, cfg)
    np.testing.assert_array_equal(w1, w2)
    # In-process copies KEEP the fitted model (only pickling drops it).
    import copy

    assert copy.copy(r).model is r.model
    assert copy.deepcopy(r).model is r.model
