"""Numerical fault containment (ISSUE 3): in-loop health flags, rollback-
and-retry recovery, and the deterministic fault-injection harness.

Acceptance contract: each injected fault (NaN-after-iter, singular
covariance, poisoned stream block) is DETECTED via the health bitmask and
RECOVERED by the escalation ladder, with the recovered run's final loglik
within tolerance of an uninterrupted run -- and with ``recovery="off"``
the same injections raise :class:`NumericalFaultError` instead of
returning a NaN model (the reference silently "converges" on poison:
``|change| > epsilon`` is false for NaN change, gaussian.cu:532). Health
flags are exact across execution paths: the sharded mesh's psum-OR'd
counter vector equals the single-device run's on identical data, and a
clean run's health section is all-zero.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu import GMMConfig, NumericalFaultError, fit_gmm, health
from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host
from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel
from cuda_gmm_mpi_tpu.telemetry import read_stream, validate_stream
from cuda_gmm_mpi_tpu.testing import faults

from .conftest import make_blobs


@pytest.fixture(scope="module")
def events():
    rng = np.random.default_rng(7)
    centers = rng.normal(scale=8.0, size=(3, 3))
    return (centers[rng.integers(0, 3, 1536)]
            + rng.normal(size=(1536, 3))).astype(np.float64)


def base_cfg(**kw):
    return GMMConfig(min_iters=4, max_iters=12, chunk_size=256,
                     dtype="float64", **kw)


# ---------------------------------------------------------------------------
# Unit layer: flag packing, the injection plan, per-lane detectors.
# ---------------------------------------------------------------------------

def test_pack_word_roundtrip():
    counts = np.zeros(health.NUM_FLAGS, np.int64)
    assert health.pack_word(counts) == 0
    assert health.flag_names(0) == []
    assert not health.word_is_fatal(0)

    counts[health.NONFINITE_LOGLIK] = 3
    counts[health.EMPTY_CLUSTER] = 1
    word = health.pack_word(counts)
    assert word == (1 << health.NONFINITE_LOGLIK) | (1 << health.EMPTY_CLUSTER)
    assert health.flag_names(word) == ["nonfinite_loglik", "empty_cluster"]
    assert health.word_is_fatal(word)  # loglik lane is fatal
    assert not health.word_is_fatal(1 << health.EMPTY_CLUSTER)
    assert health.counts_dict(counts) == {"nonfinite_loglik": 3,
                                          "empty_cluster": 1}
    # device-side packing agrees with the host-side packing
    assert int(health.pack_word_traced(jnp.asarray(counts))) == word


def test_fault_plan_budget_and_match():
    with faults.use({"checkpoint_eio": {"step": 4, "times": 2}}) as plan:
        assert faults.take("checkpoint_eio", step=3) is None  # no match
        assert faults.take("checkpoint_eio", step=4) is not None
        assert faults.take("checkpoint_eio", step=4) is not None
        assert faults.take("checkpoint_eio", step=4) is None  # budget spent
        assert plan.fired["checkpoint_eio"] == 2
    assert faults.take("checkpoint_eio", step=4) is None  # cleared
    with pytest.raises(ValueError):
        faults.FaultPlan({"not_a_fault": {}})


def test_state_lane_detectors(events):
    """empty_cluster and cov_dynamic_range are informational (non-fatal)
    lanes computed from the state; nonfinite_params is the fatal one."""
    state = seed_clusters_host(events, 4)
    clean = np.asarray(health.state_counts(state))
    assert (clean == 0).all()

    # one active cluster with a NaN mean -> nonfinite_params (fatal)
    bad = state.replace(means=state.means.at[1, 0].set(jnp.nan))
    c = np.asarray(health.state_counts(bad))
    assert c[health.NONFINITE_PARAMS] == 1
    assert bool(health.fatal(jnp.asarray(c)))

    # covariance diagonal spanning > dynamic_range**2 -> cov_dynamic_range
    wide = state.replace(R=state.R.at[2, 0, 0].set(1e12))
    c = np.asarray(health.state_counts(wide, dynamic_range=1e3))
    assert c[health.COV_DYNAMIC_RANGE] == 1
    assert not bool(health.fatal(jnp.asarray(c)))

    # soft count below the 0.5 membership floor -> empty_cluster
    c = np.asarray(health.state_counts(state, Nk=state.N.at[0].set(0.0)))
    assert c[health.EMPTY_CLUSTER] == 1
    assert not bool(health.fatal(jnp.asarray(c)))


def test_sanitized_lanes_counted(events):
    """The E-step's non-finite log-sum-exp guard is counted, not silent:
    a poisoned cluster makes every affected row report through the
    SANITIZED_LANES health lane (pre-containment code zeroed them)."""
    from cuda_gmm_mpi_tpu.ops.mstep import chunk_stats

    state = seed_clusters_host(events, 4)
    stats = chunk_stats(state, jnp.asarray(events))
    assert int(stats.sanitized) == 0
    poisoned = state.replace(Rinv=state.Rinv.at[1].set(jnp.inf))
    stats = chunk_stats(poisoned, jnp.asarray(events))
    assert int(stats.sanitized) > 0


# ---------------------------------------------------------------------------
# The NaN-converges bug (satellite 1): a non-finite loglik must stop the
# EM loop as FATAL, never exit it as "converged".
# ---------------------------------------------------------------------------

def test_nan_loglik_does_not_converge(events):
    """Injected NaN at iteration 2 with min_iters=1: the reference's
    ``|change| > epsilon`` predicate is false for NaN change, so the old
    loop exited as converged with NaN parameters. Now the fatal health
    flag short-circuits the while_loop at the poisoned iteration."""
    cfg = base_cfg()
    model = GMMModel(cfg)
    chunks, wts = chunk_events(events, cfg.chunk_size)
    state = seed_clusters_host(events, 4)
    with faults.use({"nan_loglik": {"iter": 2}}):
        _, ll, iters = model.run_em(
            state, jnp.asarray(chunks), jnp.asarray(wts),
            convergence_epsilon(*events.shape), min_iters=1, max_iters=10)
    counts = np.asarray(jax.device_get(model.last_health))
    assert not np.isfinite(float(ll))
    assert counts[health.NONFINITE_LOGLIK] >= 1
    assert health.word_is_fatal(health.pack_word(counts))
    # stopped AT the poisoned iteration, not at max_iters and not via the
    # NaN-compares-false "convergence" of the reference
    assert int(iters) == 2


# ---------------------------------------------------------------------------
# Injected fault x recovery (the tentpole acceptance matrix).
# ---------------------------------------------------------------------------

FAULTS = [
    ("nan_loglik", {"nan_loglik": {"iter": 2}}, {}),
    ("singular_cov", {"singular_cov": {"cluster": 1}}, {}),
    ("poison_block", {"poison_block": {"block": 0}},
     {"stream_events": True}),
    ("fused_nan", {"nan_loglik": {"iter": 2}}, {"fused_sweep": True}),
]


@pytest.fixture(scope="module")
def clean_loglik(events):
    r = fit_gmm(events, 5, 2, config=base_cfg())
    assert r.health["flags"] == 0 and not r.health["fatal"]
    assert r.health["recoveries"] == 0 and r.health["io_retries"] == 0
    return r.final_loglik


@pytest.mark.parametrize("name,spec,extra", FAULTS,
                         ids=[f[0] for f in FAULTS])
def test_fault_detected_and_recovered(events, clean_loglik, name, spec,
                                      extra):
    """Every injected fault is detected via the bitmask and recovered by
    the ladder (the fused path recovers by host-sweep fallback); the
    recovered run's final loglik matches an uninterrupted run."""
    with faults.use(spec) as plan:
        r = fit_gmm(events, 5, 2, config=base_cfg(**extra))
    assert plan.fired[next(iter(spec))] >= 1  # the fault actually fired
    assert r.health["recoveries"] >= 1, r.health
    assert r.health["fatal"], r.health  # the fault was OBSERVED...
    assert np.isfinite(r.final_loglik)  # ...and the model is clean
    np.testing.assert_allclose(r.final_loglik, clean_loglik, rtol=1e-4)
    assert np.isfinite(np.asarray(r.means)).all()


@pytest.mark.parametrize("name,spec,extra", FAULTS,
                         ids=[f[0] for f in FAULTS])
def test_recovery_off_fails_loudly(events, name, spec, extra):
    """recovery='off': the same injections raise NumericalFaultError with
    a diagnostic bundle instead of returning a NaN model."""
    with faults.use(spec):
        with pytest.raises(NumericalFaultError) as ei:
            fit_gmm(events, 5, 2, config=base_cfg(recovery="off", **extra))
    bundle = ei.value.bundle
    assert bundle["flags"] and bundle["flag_names"]
    assert health.word_is_fatal(bundle["flags"])
    assert "nonfinite_loglik" in str(ei.value)


def test_escalation_second_rung(events, clean_loglik, tmp_path):
    """times=2: the fault survives the pure-regularization rung (same
    numerics re-observe it) and rung 2 (quad_mode='centered') clears it --
    the telemetry stream records the full attempt ladder."""
    mf = tmp_path / "m.jsonl"
    with faults.use({"nan_loglik": {"iter": 2, "times": 2}}):
        r = fit_gmm(events, 5, 2,
                    config=base_cfg(metrics_file=str(mf)))
    assert r.health["recoveries"] >= 1
    np.testing.assert_allclose(r.final_loglik, clean_loglik, rtol=1e-4)
    records = read_stream(str(mf))
    assert validate_stream(records) == []
    rec_ev = [x for x in records if x["event"] == "recovery"]
    assert [(x["attempt"], x["action"], x["outcome"]) for x in rec_ev] == [
        (1, "regularize", "fatal"), (2, "centered", "recovered")]
    # the observed fault also rides the stream and the summary
    assert any(x["event"] == "health" and x["where"] == "em"
               for x in records)
    summary = [x for x in records if x["event"] == "run_summary"][-1]
    assert summary["health"]["recoveries"] == 1


def test_escalation_exhausted_raises(events):
    """A fault that survives every rung (times covers all traces) raises
    with the full per-attempt history in the bundle."""
    with faults.use({"nan_loglik": {"iter": 2, "times": 10}}):
        with pytest.raises(NumericalFaultError) as ei:
            fit_gmm(events, 5, 2, config=base_cfg())
    attempts = ei.value.bundle["attempts"]
    assert [a["action"] for a in attempts] == [
        "regularize", "centered", "highest"]
    assert all(a["outcome"] == "fatal" for a in attempts)


def test_truncated_ladder(events):
    """max_recovery_attempts bounds the ladder."""
    with faults.use({"nan_loglik": {"iter": 2, "times": 10}}):
        with pytest.raises(NumericalFaultError) as ei:
            fit_gmm(events, 5, 2,
                    config=base_cfg(max_recovery_attempts=1))
    assert [a["action"] for a in ei.value.bundle["attempts"]] == [
        "regularize"]


# ---------------------------------------------------------------------------
# psum-OR parity: sharded flag counters == single-device counters.
# ---------------------------------------------------------------------------

def _poison(state):
    """A singular covariance with the Rinv a real inversion produces."""
    return state.replace(R=state.R.at[1].set(0.0),
                         Rinv=state.Rinv.at[1].set(jnp.inf))


def _em_health_single(data, poisoned):
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                    dtype="float64")
    model = GMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size)
    state = seed_clusters_host(data, 4)
    if poisoned:
        state = _poison(state)
    model.run_em(state, jnp.asarray(chunks), jnp.asarray(wts),
                 convergence_epsilon(*data.shape))
    return np.asarray(jax.device_get(model.last_health))


def _em_health_sharded(data, poisoned, mesh_shape):
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=128,
                    dtype="float64", mesh_shape=mesh_shape)
    model = ShardedGMMModel(cfg)
    chunks, wts = chunk_events(data, cfg.chunk_size, model.data_size)
    state = seed_clusters_host(data, 4)
    if poisoned:
        state = _poison(state)
    state, chunks, wts = model.prepare(state, chunks, wts)
    model.run_em(state, chunks, wts, convergence_epsilon(*data.shape))
    return np.asarray(jax.device_get(model.last_health))


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (1, 8)])
def test_psum_or_parity(rng, mesh_shape):
    """The sharded mesh's psum-OR'd health counters equal the
    single-device run's EXACTLY, clean and poisoned, on identical data:
    event lanes ride the data-axis stats psum, cluster lanes the
    cluster-axis psum inside health.state_counts -- each shard counts a
    disjoint slice, so the sum reproduces the global count."""
    data, _ = make_blobs(rng, n=1024, d=3, k=4)
    for poisoned in (False, True):
        h0 = _em_health_single(data, poisoned)
        h1 = _em_health_sharded(data, poisoned, mesh_shape)
        np.testing.assert_array_equal(h1, h0)
        assert health.pack_word(h1) == health.pack_word(h0)
        if poisoned:
            assert health.word_is_fatal(health.pack_word(h1))


def test_sharded_fit_recovers(events, clean_loglik):
    """End-to-end on the 8-fake-device mesh: injected singular covariance
    is detected through the psum-OR aggregation and recovered."""
    with faults.use({"singular_cov": {"cluster": 1}}) as plan:
        r = fit_gmm(events, 5, 2,
                    config=base_cfg(mesh_shape=(4, 2)))
    assert plan.fired["singular_cov"] == 1
    assert r.health["fatal"] and r.health["recoveries"] >= 1
    np.testing.assert_allclose(r.final_loglik, clean_loglik, rtol=1e-4)


# ---------------------------------------------------------------------------
# Selection guards + empty-cluster handling.
# ---------------------------------------------------------------------------

def test_nonfinite_score_never_wins(events, monkeypatch):
    """NaN compares false both ways, so an unguarded NaN score at the
    first K would capture the best-model slot and never be displaced.
    The guard skips it with a health event instead (satellite 3)."""
    from cuda_gmm_mpi_tpu.models import order_search
    from cuda_gmm_mpi_tpu.ops.formulas import model_score as real_score

    def poisoned_score(ll, k, *a, **kw):
        return float("nan") if int(k) == 5 else real_score(ll, k, *a, **kw)

    monkeypatch.setattr(order_search, "model_score", poisoned_score)
    r = fit_gmm(events, 5, 2, config=base_cfg())
    assert r.ideal_num_clusters != 5  # the poisoned K did not win
    assert np.isfinite(r.min_rissanen)
    assert r.health["flags"] & (1 << health.NONFINITE_SCORE)
    assert not r.health["fatal"]  # score poisoning alone is not fatal


def test_fused_sweep_flags_nonfinite_score(events):
    """The fused sweep's on-device best-save rule carries the same guard:
    an injected NaN loglik yields a NaN score whose K is excluded and
    flagged (the health word rides the emitted per-K device log)."""
    with faults.use({"nan_loglik": {"iter": 2}}):
        r = fit_gmm(events, 5, 2, config=base_cfg(fused_sweep=True))
    assert r.health["flags"] & (1 << health.NONFINITE_SCORE)
    assert np.isfinite(r.min_rissanen)


def test_reseed_empty_clusters(events):
    """reseed_empty_clusters relocates an empty active cluster onto the
    worst-fit events (deterministically) instead of eliminating it."""
    cfg = base_cfg()
    model = GMMModel(cfg)
    state = seed_clusters_host(events, 4)
    # cluster 2 collapsed: zero soft count, mean far from all data
    state = state.replace(N=state.N.at[2].set(0.0),
                          means=state.means.at[2].set(1e5))
    chunks, _ = chunk_events(events, cfg.chunk_size)
    new_state, n = health.reseed_empty_clusters(model, state,
                                                jnp.asarray(chunks))
    assert n == 1
    new_means = np.asarray(new_state.means)
    # the reseeded mean sits on an actual event row now
    d = np.abs(events[:, None, :] - new_means[2][None, None, :]).sum(-1)
    assert d.min() < 1e-9
    assert np.asarray(new_state.N)[2] > 0
    # nothing to do on a healthy state
    _, n2 = health.reseed_empty_clusters(model, new_state.replace(
        N=jnp.ones_like(new_state.N)), jnp.asarray(chunks))
    assert n2 == 0


# ---------------------------------------------------------------------------
# Telemetry surfaces: stream validity + `gmm report` rendering.
# ---------------------------------------------------------------------------

def test_health_events_render_in_report(events, tmp_path, capsys):
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    mf = tmp_path / "m.jsonl"
    with faults.use({"singular_cov": {"cluster": 1}}):
        r = fit_gmm(events, 5, 2, config=base_cfg(metrics_file=str(mf)))
    assert r.health["fatal"] and r.health["recoveries"] >= 1
    records = read_stream(str(mf))
    assert validate_stream(records) == []
    assert any(x["event"] == "health" for x in records)
    assert any(x["event"] == "recovery" for x in records)

    assert cli_main(["report", str(mf)]) == 0
    out = capsys.readouterr().out
    assert "Health / recovery" in out
    assert "recovery K=" in out
    assert "nonfinite_loglik" in out


def test_clean_report_says_clean(events, tmp_path, capsys):
    from cuda_gmm_mpi_tpu.cli import main as cli_main

    mf = tmp_path / "m.jsonl"
    fit_gmm(events, 4, 2, config=base_cfg(metrics_file=str(mf)))
    assert cli_main(["report", str(mf)]) == 0
    out = capsys.readouterr().out
    assert "Health: clean (all flags zero)" in out


# ---------------------------------------------------------------------------
# Slow end-to-end: kill + poison + resume in one run.
# ---------------------------------------------------------------------------

POISON_WORKER = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.models import fit_gmm

ckdir = sys.argv[1]
rng = np.random.default_rng(77)
centers = rng.normal(scale=9.0, size=(4, 3))
data = (centers[rng.integers(0, 4, 4000)]
        + rng.normal(size=(4000, 3))).astype(np.float64)
cfg = GMMConfig(min_iters=6, max_iters=6, chunk_size=512, dtype="float64",
                checkpoint_dir=ckdir, enable_print=True)
r = fit_gmm(data, 12, 2, config=cfg)
print(json.dumps({
    "ideal_k": r.ideal_num_clusters,
    "min_rissanen": r.min_rissanen,
    "final_loglik": r.final_loglik,
    "health": r.health,
    "sweep_ks": [int(row[0]) for row in r.sweep_log],
}))
"""


@pytest.mark.slow
def test_kill_poison_resume_end_to_end(tmp_path):
    """The whole robustness story in one run: a worker with an armed
    NaN injection (GMM_FAULTS env -- the subprocess activation path)
    recovers in-flight, is then SIGKILLed mid-sweep, and the restarted
    process resumes from the surviving checkpoint to the uninterrupted
    answer."""
    import os
    import signal
    import subprocess
    import sys
    import time

    from .conftest import communicate_or_kill, worker_env
    from .test_failure_recovery import _steps_on_disk

    ck = str(tmp_path / "ck")
    sweep_dir = os.path.join(ck, "sweep")
    env = worker_env()
    env["GMM_FAULTS"] = json.dumps({"nan_loglik": {"iter": 2}})

    p = subprocess.Popen([sys.executable, "-c", POISON_WORKER, ck],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, text=True)
    deadline = time.time() + 300
    try:
        while time.time() < deadline:
            if len(_steps_on_disk(sweep_dir)) >= 2:
                break
            if p.poll() is not None:
                out, err = p.communicate()
                raise AssertionError(
                    f"worker exited before kill (rc={p.returncode}):\n"
                    f"{out}\n{err[-3000:]}")
            time.sleep(0.05)
        else:
            raise AssertionError("no checkpoint appeared within timeout")
        os.kill(p.pid, signal.SIGKILL)
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=60)
    assert p.returncode != 0

    # Resume (no faults armed) completes from the surviving checkpoint.
    p2 = subprocess.Popen([sys.executable, "-c", POISON_WORKER, ck],
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          env=worker_env(), text=True)
    out, err = communicate_or_kill(p2, timeout=600)
    assert p2.returncode == 0, f"resume failed:\n{out}\n{err[-3000:]}"
    resumed = json.loads(out.splitlines()[-1])
    assert len(resumed["sweep_ks"]) == 11

    # Ground truth: clean uninterrupted run.
    p3 = subprocess.Popen(
        [sys.executable, "-c", POISON_WORKER, str(tmp_path / "ck_ref")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=worker_env(), text=True)
    out3, err3 = communicate_or_kill(p3, timeout=600)
    assert p3.returncode == 0, f"reference failed:\n{out3}\n{err3[-3000:]}"
    ref = json.loads(out3.splitlines()[-1])
    assert ref["health"]["flags"] == 0

    assert resumed["ideal_k"] == ref["ideal_k"]
    # rtol matches the in-process recovery tests: the rung's variance-
    # floor boost perturbs the recovered trajectory at the ~1e-6 level,
    # it does not reproduce the clean run bit-for-bit.
    np.testing.assert_allclose(resumed["min_rissanen"],
                               ref["min_rissanen"], rtol=1e-4)
    np.testing.assert_allclose(resumed["final_loglik"],
                               ref["final_loglik"], rtol=1e-4)
