"""M-step sufficient stats + parameter update vs the NumPy oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from cuda_gmm_mpi_tpu.ops.estep import posteriors
from cuda_gmm_mpi_tpu.ops.mstep import (
    accumulate_stats, apply_mstep, chunk_stats,
)

from .reference_impl import np_estep, np_mstep
from .test_estep import make_state


def as_params(state):
    return {
        "N": np.asarray(state.N), "pi": np.asarray(state.pi),
        "constant": np.asarray(state.constant),
        "avgvar": np.asarray(state.avgvar),
        "means": np.asarray(state.means), "R": np.asarray(state.R),
        "Rinv": np.asarray(state.Rinv),
    }


def test_chunk_stats_match_oracle(rng):
    k, d, n = 4, 3, 200
    state = make_state(rng, k, d)
    x = rng.normal(scale=2.0, size=(n, d))
    stats = chunk_stats(state, jnp.asarray(x))
    w, ll = np_estep(as_params(state), x)
    np.testing.assert_allclose(float(stats.loglik), ll, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(stats.Nk), w.sum(0), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(stats.M1), w.T @ x, rtol=1e-9)
    M2 = np.einsum("nk,nd,ne->kde", w, x, x)
    np.testing.assert_allclose(np.asarray(stats.M2), M2, rtol=1e-8, atol=1e-10)


def test_packed_quad_mode_matches_expanded(rng):
    """quad_mode='packed' (symmetric-half features) is exact vs 'expanded'.

    The packed path computes each x_i x_j product once (doubled off-diagonal
    Rinv weights in q; mirrored-by-gather M2), so in float64 it must agree
    with the full outer-product path to reduction-order tolerance, and its
    M2 must be exactly symmetric by construction.
    """
    k, d, n = 5, 7, 300
    state = make_state(rng, k, d)
    x = rng.normal(scale=2.0, size=(n, d))
    a = chunk_stats(state, jnp.asarray(x), quad_mode="expanded")
    b = chunk_stats(state, jnp.asarray(x), quad_mode="packed")
    np.testing.assert_allclose(float(b.loglik), float(a.loglik), rtol=1e-12)
    for name in ("Nk", "M1", "M2"):
        np.testing.assert_allclose(
            np.asarray(getattr(b, name)), np.asarray(getattr(a, name)),
            rtol=1e-10, atol=1e-12,
        )
    M2 = np.asarray(b.M2)
    assert np.array_equal(M2, M2.transpose(0, 2, 1))


def test_sym_packing_roundtrip(rng):
    """pack_features ordering matches triu_indices; unpack_sym inverts it."""
    from cuda_gmm_mpi_tpu.ops.estep import (
        pack_features, pack_sym_weighted, unpack_sym,
    )

    d, n, k = 6, 20, 3
    x = rng.normal(size=(n, d))
    iu = np.triu_indices(d)
    xt = np.asarray(pack_features(jnp.asarray(x)))
    np.testing.assert_array_equal(xt, x[:, iu[0]] * x[:, iu[1]])

    A = np.stack([np.diag(np.full(d, 2.0)) + rng.normal(size=(d, d))
                  for _ in range(k)])
    A = (A + A.transpose(0, 2, 1)) / 2
    packed = np.asarray(pack_sym_weighted(jnp.asarray(A)))
    # packed_features . packed_A reproduces the full quadratic form
    q_full = np.einsum("ni,nj,kij->nk", x, x, A)
    np.testing.assert_allclose(xt @ packed.T, q_full, rtol=1e-12)
    # unpack of the undoubled triangle restores the symmetric matrix
    tri = np.stack([a[iu] for a in A])
    np.testing.assert_array_equal(
        np.asarray(unpack_sym(jnp.asarray(tri), d)), A)


def test_accumulate_equals_single_chunk(rng):
    k, d, n, b = 3, 4, 96, 32
    state = make_state(rng, k, d)
    x = rng.normal(size=(n, d))
    whole = chunk_stats(state, jnp.asarray(x))
    chunked = accumulate_stats(
        state, jnp.asarray(x.reshape(n // b, b, d)),
        jnp.ones((n // b, b)),
    )
    for name in ("loglik", "Nk", "M1", "M2"):
        np.testing.assert_allclose(
            np.asarray(getattr(chunked, name)), np.asarray(getattr(whole, name)),
            rtol=1e-9, atol=1e-12,
        )


def test_padding_mask_ignored(rng):
    k, d, n, b = 3, 3, 50, 32
    state = make_state(rng, k, d)
    x = rng.normal(size=(n, d))
    pad = (-n) % b
    xp = np.concatenate([x, np.zeros((pad, d))]).reshape(-1, b, d)
    wts = np.concatenate([np.ones(n), np.zeros(pad)]).reshape(-1, b)
    padded = accumulate_stats(state, jnp.asarray(xp), jnp.asarray(wts))
    exact = chunk_stats(state, jnp.asarray(x))
    np.testing.assert_allclose(float(padded.loglik), float(exact.loglik),
                               rtol=1e-10)
    np.testing.assert_allclose(np.asarray(padded.Nk), np.asarray(exact.Nk),
                               rtol=1e-10)
    np.testing.assert_allclose(np.asarray(padded.M2), np.asarray(exact.M2),
                               rtol=1e-9)


@pytest.mark.parametrize("diag_only", [False, True])
def test_apply_mstep_matches_oracle(rng, diag_only):
    k, d, n = 4, 3, 300
    state = make_state(rng, k, d)
    state = state.replace(avgvar=jnp.full((k,), 0.37))
    if diag_only:
        # Diag mode assumes a diagonal model state (DIAG_ONLY builds never
        # produce off-diagonals); diagonalize so oracle and op see the same w.
        R = np.asarray(state.R)
        Rd = np.stack([np.diag(np.diag(R[c])) for c in range(k)])
        const = -d * 0.5 * np.log(2 * np.pi) - 0.5 * np.log(
            np.diagonal(Rd, axis1=1, axis2=2)
        ).sum(1)
        state = state.replace(
            R=jnp.asarray(Rd), Rinv=jnp.asarray(np.linalg.inv(Rd)),
            constant=jnp.asarray(const),
        )
    x = rng.normal(scale=2.0, size=(n, d))
    params = as_params(state)
    w, _ = np_estep(params, x)
    expected = np_mstep(params, x, w, diag_only=diag_only)

    stats = chunk_stats(state, jnp.asarray(x), diag_only=diag_only)
    out = apply_mstep(state, stats, diag_only=diag_only)
    np.testing.assert_allclose(np.asarray(out.N), expected["N"], rtol=1e-9)
    np.testing.assert_allclose(np.asarray(out.means), expected["means"],
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.asarray(out.R), expected["R"], rtol=1e-7,
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(out.Rinv), expected["Rinv"],
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(np.asarray(out.constant), expected["constant"],
                               rtol=1e-8)
    np.testing.assert_allclose(np.asarray(out.pi), expected["pi"], rtol=1e-9)


def test_empty_cluster_guards(rng):
    """N<0.5 -> means 0, R identity; 0.5<=N<1 -> cov sums zeroed (reference
    gaussian.cu:614-618, 663-679; gaussian_kernel.cu:658-668)."""
    k, d, n = 3, 3, 40
    state = make_state(rng, k, d)
    state = state.replace(avgvar=jnp.full((k,), 0.2))
    x = rng.normal(size=(n, d))
    w = np.zeros((n, k))
    w[:, 0] = 1.0  # cluster 0 owns everything
    w[0, 0] = 0.3
    w[0, 1] = 0.7  # cluster 1: N = 0.7 (between 0.5 and 1)
    # cluster 2: N = 0 (empty)
    from cuda_gmm_mpi_tpu.ops.mstep import SuffStats

    stats = SuffStats(
        loglik=jnp.asarray(0.0),
        Nk=jnp.asarray(w.sum(0)),
        M1=jnp.asarray(w.T @ x),
        M2=jnp.asarray(np.einsum("nk,nd,ne->kde", w, x, x)),
    )
    out = apply_mstep(state, stats)
    # empty cluster -> identity R, zero means
    np.testing.assert_allclose(np.asarray(out.R[2]), np.eye(d))
    np.testing.assert_allclose(np.asarray(out.means[2]), 0.0)
    # 0.5 < N < 1: cov sums zeroed, R = avgvar*I/N
    np.testing.assert_allclose(
        np.asarray(out.R[1]), 0.2 * np.eye(d) / 0.7, rtol=1e-9
    )
    # pi floor for empty
    assert float(out.pi[2]) == pytest.approx(1e-10)
