"""Worker process for the 2-process jax.distributed integration test.

Each worker is one "host" of a simulated 2-host cluster (the TPU-native
``mpirun`` rank, SURVEY.md SS2.8): it pins the CPU platform with 2 local
devices, joins the coordination service, loads ONLY its host_slice of the
dataset, and runs the sharded EM loop over the global 4-device mesh -- the
full multi-controller path (jax.distributed.initialize +
host_local_array_to_global_array + cross-process psum) that single-process
tests cannot reach.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
Prints one line: RESULT pid=<i> ll=<loglik> iters=<n> means=<csv of row 0>
"""

import sys


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import jax

    # Must run before the backend initializes (the image's sitecustomize
    # preloads jax pinned elsewhere; config.update is authoritative).
    jax.config.update("jax_platforms", "cpu")
    from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices

    force_cpu_devices(2)
    jax.config.update("jax_enable_x64", True)

    from cuda_gmm_mpi_tpu.parallel import distributed

    got_pid, got_nproc = distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert (got_pid, got_nproc) == (pid, nproc), (got_pid, got_nproc)
    assert len(jax.devices()) == 2 * nproc, jax.devices()

    import numpy as np

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host
    from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel, make_mesh
    from cuda_gmm_mpi_tpu.parallel.distributed import host_chunk_bounds

    # Deterministic dataset, identical on every host (stands in for a shared
    # input file); only the host's slice is chunked/uploaded. 509 events:
    # NOT divisible by chunks/hosts/devices, so the remainder path (tail
    # host pads + masks) is what's exercised.
    n, d, k = 509, 3, 3
    rng = np.random.default_rng(1234)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (
        centers[rng.integers(0, k, n)] + rng.normal(size=(n, d))
    ).astype(np.float64)

    # Optional 4th arg selects the mesh: "data" (default, all devices on the
    # event axis) or "2d" (data x cluster: 2-D sharding across the REAL
    # process boundary -- each host owns one data-axis row, the cluster axis
    # lives within a host).
    mesh_kind = sys.argv[4] if len(sys.argv) > 4 else "data"
    cfg = GMMConfig(min_iters=4, max_iters=4, chunk_size=64, dtype="float64")
    if mesh_kind == "2d":
        mesh = make_mesh((nproc, 2))
    elif mesh_kind == "data":
        mesh = make_mesh()  # all 2*nproc global devices on the data axis
    else:
        raise ValueError(f"unknown mesh_kind {mesh_kind!r}")
    model = ShardedGMMModel(cfg, mesh=mesh)

    start, stop, num_chunks = host_chunk_bounds(
        n, cfg.chunk_size, mesh.shape["data"], pid, nproc
    )
    local_chunks, local_wts = chunk_events(
        data[start:stop], cfg.chunk_size, num_chunks=num_chunks
    )
    state = seed_clusters_host(data, k)  # seeding uses global moments
    state, chunks, wts = model.prepare(state, local_chunks, local_wts,
                                       host_local=True)
    eps = convergence_epsilon(n, d)

    s, ll, iters = model.run_em(state, chunks, wts, eps)
    jax.block_until_ready(s)
    means0 = np.asarray(jax.device_get(s.means))[0]
    print(
        f"RESULT pid={pid} ll={float(ll):.10e} iters={int(iters)} "
        f"means={','.join(f'{v:.12e}' for v in means0)}",
        flush=True,
    )
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
