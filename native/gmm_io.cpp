// Native I/O runtime for the TPU GMM framework.
//
// The reference's data path is native C++ (readData.cpp); this library keeps
// that property for the TPU build: hot text parsing and result formatting run
// in C++, exposed through a minimal C ABI consumed via ctypes
// (cuda_gmm_mpi_tpu/io/native.py). Semantics match the reference readers:
//   - dispatch on a trailing "bin" in the filename (readData.cpp:28)
//   - BIN: int32 nevents, int32 ndims, float32 row-major payload
//     (readData.cpp:35-47)
//   - CSV: dims counted from the first line, FIRST LINE DROPPED as a header
//     (readData.cpp:84), blank lines skipped, atof-style field parsing
//     (strtof prefix semantics), ragged rows -> error (readData.cpp:104-107)
// and the .results writer (gaussian.cu:1042-1059): "%f" CSV of the event data,
// a tab, "%f" CSV of the per-cluster memberships, one line per event.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// rc: 0 ok, 1 open/alloc failure, 2 malformed content
int gmm_read_data(const char* path, int64_t* n_out, int64_t* d_out,
                  float** data_out);
// Shape probe without loading the payload: BIN reads the 8-byte header; CSV
// streams the file in fixed-size blocks counting non-blank lines (O(1) RAM).
int gmm_data_shape(const char* path, int64_t* n_out, int64_t* d_out);
// Range read of rows [start, stop): the per-host loading primitive (each host
// of a multi-controller run reads ONLY its slice -- the anti-MPI_Bcast,
// reference gaussian.cu:191-201 broadcast the whole dataset). BIN seeks the
// row range directly (readData.cpp:35-47 layout); CSV streams blocks and
// parses only in-range rows. Peak memory is O(stop-start), never O(file).
// stop < 0 means "to the end of the file" (single pass, no prior shape
// probe); *n_out receives the number of rows actually read.
int gmm_read_range(const char* path, int64_t start, int64_t stop,
                   int64_t* n_out, int64_t* d_out, float** data_out);
void gmm_free(float* p);
int gmm_write_results(const char* path, const float* data, const float* memb,
                      int64_t n, int64_t d, int64_t k);
// Streaming variant: open once, append event blocks, close. Bounded memory
// for arbitrarily large N (the 10M x 128 posterior matrix never exists).
void* gmm_results_open(const char* path);
int gmm_results_append(void* handle, const float* data, const float* memb,
                       int64_t n, int64_t d, int64_t k);
int gmm_results_close(void* handle);

}  // extern "C"

namespace {

// malloc for rows*d floats with explicit overflow checks (a crafted header
// or absurd caller range must fail cleanly, not wrap the size_t multiply).
float* alloc_rows(int64_t rows, int64_t d) {
  if (rows < 0 || d <= 0) return nullptr;
  const uint64_t urows = static_cast<uint64_t>(rows ? rows : 1);
  const uint64_t ud = static_cast<uint64_t>(d);
  if (ud > SIZE_MAX / sizeof(float)) return nullptr;
  if (urows > SIZE_MAX / (ud * sizeof(float))) return nullptr;
  return static_cast<float*>(std::malloc(urows * ud * sizeof(float)));
}

int read_bin(const char* path, int64_t* n_out, int64_t* d_out,
             float** data_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  int32_t header[2];
  if (std::fread(header, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return 2;
  }
  const int64_t n = header[0], d = header[1];
  if (n <= 0 || d <= 0) {
    std::fclose(f);
    return 2;
  }
  const size_t count = static_cast<size_t>(n) * static_cast<size_t>(d);
  float* data = alloc_rows(n, d);
  if (!data) {
    std::fclose(f);
    return 1;
  }
  if (std::fread(data, sizeof(float), count, f) != count) {
    std::free(data);
    std::fclose(f);
    return 2;
  }
  std::fclose(f);
  *n_out = n;
  *d_out = d;
  *data_out = data;
  return 0;
}

// Count comma-separated fields on [p, end).
int64_t count_fields(const char* p, const char* end) {
  int64_t fields = 1;
  for (; p < end; ++p)
    if (*p == ',') ++fields;
  return fields;
}

// Parse one field [q, fe) with atof prefix semantics (readData.cpp:108).
// Bounded: strtof runs on a NUL-terminated copy, so it can never skip a
// line's trailing empty field into the next line (strtof treats '\n' as
// leading whitespace) or scan past a block buffer's end.
float parse_field(const char* q, const char* fe) {
  char tmp[64];
  const size_t len = static_cast<size_t>(fe - q);
  if (len == 0) return 0.0f;
  char* next = nullptr;
  float v;
  if (len <= sizeof(tmp) - 1) {
    std::memcpy(tmp, q, len);
    tmp[len] = '\0';
    v = std::strtof(tmp, &next);
    return next == tmp ? 0.0f : v;
  }
  // Rare: a field longer than 63 chars (e.g. digit-padded mantissa whose
  // exponent falls past any fixed cutoff) -- heap-copy so nothing truncates.
  std::string s(q, fe);
  v = std::strtof(s.c_str(), &next);
  return next == s.c_str() ? 0.0f : v;
}

// Parse one CSV line [q, qe) of exactly d fields into out. Returns 0, or 2 on
// a ragged row.
int parse_csv_row(const char* q, const char* qe, int64_t d, float* out) {
  if (count_fields(q, qe) != d) return 2;
  for (int64_t j = 0; j < d; ++j) {
    const char* comma = static_cast<const char*>(
        std::memchr(q, ',', static_cast<size_t>(qe - q)));
    const char* fe = comma ? comma : qe;
    out[j] = parse_field(q, fe);
    q = comma ? comma + 1 : qe;
  }
  return 0;
}

int read_csv(const char* path, int64_t* n_out, int64_t* d_out,
             float** data_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  std::fseek(f, 0, SEEK_END);
  const long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return 2;
  }
  std::fclose(f);

  // Split into non-empty lines (skip blanks, strip \r) -- readData.cpp:58-64.
  const char* p = buf.data();
  const char* const end = p + buf.size();
  std::vector<std::pair<const char*, const char*>> lines;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* stop = nl ? nl : end;
    const char* right = stop;
    while (right > p && (right[-1] == '\r')) --right;
    if (right > p) lines.emplace_back(p, right);
    p = nl ? nl + 1 : end;
  }
  if (lines.empty()) return 2;

  const int64_t d = count_fields(lines[0].first, lines[0].second);
  const int64_t n = static_cast<int64_t>(lines.size()) - 1;  // header dropped
  if (n <= 0) return 2;

  float* data = alloc_rows(n, d);
  if (!data) return 1;

  for (int64_t i = 0; i < n; ++i) {
    const char* q = lines[static_cast<size_t>(i) + 1].first;
    const char* qe = lines[static_cast<size_t>(i) + 1].second;
    if (parse_csv_row(q, qe, d, data + i * d) != 0) {
      std::free(data);
      return 2;
    }
  }
  *n_out = n;
  *d_out = d;
  *data_out = data;
  return 0;
}

// Stream a CSV file block-by-block, invoking fn(line_index, begin, end) for
// every non-blank line (index 0 = the header). fn returns 0 to continue,
// 1 to stop early (not an error), or an rc>1 to abort with that code.
// Peak memory: one 1 MiB block + the longest single line.
template <typename Fn>
int scan_csv_lines(const char* path, Fn fn) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  std::vector<char> block(1 << 20);
  std::string carry;
  int64_t line_index = 0;
  int rc = 0;
  for (;;) {
    const size_t got = std::fread(block.data(), 1, block.size(), f);
    if (got == 0) break;
    const char* p = block.data();
    const char* const end = p + got;
    while (p < end) {
      const char* nl = static_cast<const char*>(
          std::memchr(p, '\n', static_cast<size_t>(end - p)));
      if (!nl) {
        carry.append(p, end);
        break;
      }
      const char *lb, *le;
      if (!carry.empty()) {
        carry.append(p, nl);
        lb = carry.data();
        le = lb + carry.size();
      } else {
        lb = p;
        le = nl;
      }
      while (le > lb && le[-1] == '\r') --le;
      if (le > lb) {
        rc = fn(line_index++, lb, le);
        if (rc) break;
      }
      carry.clear();
      p = nl + 1;
    }
    if (rc) break;
  }
  std::fclose(f);
  if (rc == 0 && !carry.empty()) {  // final line without trailing newline
    const char* lb = carry.data();
    const char* le = lb + carry.size();
    while (le > lb && le[-1] == '\r') --le;
    if (le > lb) rc = fn(line_index++, lb, le);
  }
  return rc == 1 ? 0 : rc;  // early-stop is success
}

int bin_shape(const char* path, int64_t* n_out, int64_t* d_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  int32_t header[2];
  const bool ok = std::fread(header, sizeof(int32_t), 2, f) == 2;
  std::fclose(f);
  if (!ok || header[0] <= 0 || header[1] <= 0) return 2;
  *n_out = header[0];
  *d_out = header[1];
  return 0;
}

int bin_read_range(const char* path, int64_t start, int64_t stop,
                   int64_t* n_out, int64_t* d_out, float** data_out) {
  int64_t n = 0, d = 0;
  int rc = bin_shape(path, &n, &d);
  if (rc) return rc;
  if (stop < 0) stop = n;  // "to end" sentinel
  if (start < 0 || stop < start || stop > n) return 2;
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  const int64_t rows = stop - start;
  float* data = alloc_rows(rows, d);
  if (!data) {
    std::fclose(f);
    return 1;
  }
  const size_t count = static_cast<size_t>(rows) * static_cast<size_t>(d);
#if defined(_WIN32)
  const int seek_rc = _fseeki64(f, 8LL + start * d * 4, SEEK_SET);
#else
  const int seek_rc =
      fseeko(f, static_cast<off_t>(8) + static_cast<off_t>(start) * d * 4,
             SEEK_SET);
#endif
  if (seek_rc != 0 || std::fread(data, sizeof(float), count, f) != count) {
    std::free(data);
    std::fclose(f);
    return 2;
  }
  std::fclose(f);
  *n_out = rows;
  *d_out = d;
  *data_out = data;
  return 0;
}

// %f formatting without printf overhead: 6 fixed decimals, round-half-away.
char* format_f(char* out, double v) {
  if (v < 0) {
    *out++ = '-';
    v = -v;
  }
  // Overflow-safe for the float32 inputs we emit (fits in int64 up to ~9e12).
  if (v > 9e12) return out + std::sprintf(out, "%f", v);
  const int64_t scaled = static_cast<int64_t>(v * 1e6 + 0.5);
  const int64_t ip = scaled / 1000000, fp = scaled % 1000000;
  out += std::sprintf(out, "%lld", static_cast<long long>(ip));
  *out++ = '.';
  for (int64_t div = 100000; div >= 1; div /= 10)
    *out++ = static_cast<char>('0' + (fp / div) % 10);
  return out;
}

}  // namespace

int gmm_read_data(const char* path, int64_t* n_out, int64_t* d_out,
                  float** data_out) {
  const size_t len = std::strlen(path);
  if (len >= 3 && std::strcmp(path + len - 3, "bin") == 0)
    return read_bin(path, n_out, d_out, data_out);
  return read_csv(path, n_out, d_out, data_out);
}

int gmm_data_shape(const char* path, int64_t* n_out, int64_t* d_out) {
  const size_t len = std::strlen(path);
  if (len >= 3 && std::strcmp(path + len - 3, "bin") == 0)
    return bin_shape(path, n_out, d_out);
  int64_t lines = 0, d = 0;
  const int rc = scan_csv_lines(
      path, [&](int64_t idx, const char* lb, const char* le) -> int {
        if (idx == 0) d = count_fields(lb, le);
        ++lines;
        return 0;
      });
  if (rc) return rc;
  if (lines < 2) return 2;  // header + at least one data row
  *n_out = lines - 1;
  *d_out = d;
  return 0;
}

int gmm_read_range(const char* path, int64_t start, int64_t stop,
                   int64_t* n_out, int64_t* d_out, float** data_out) {
  const size_t len = std::strlen(path);
  if (len >= 3 && std::strcmp(path + len - 3, "bin") == 0)
    return bin_read_range(path, start, stop, n_out, d_out, data_out);
  if (start < 0 || (stop >= 0 && stop < start)) return 2;
  const bool to_end = stop < 0;
  const int64_t want = to_end ? -1 : (stop - start);
  // Initial capacity is bounded regardless of the caller's stop: rows arrive
  // from the scan, so an absurd range fails with rc=2 at EOF instead of
  // attempting a huge up-front allocation.
  int64_t cap = to_end ? 4096 : (want < 4096 ? want : 4096);
  int64_t d = 0, seen = 0, total_rows = 0;
  float* data = nullptr;
  int rc = scan_csv_lines(
      path, [&](int64_t idx, const char* lb, const char* le) -> int {
        if (idx == 0) {
          d = count_fields(lb, le);
          data = alloc_rows(cap, d);
          return data ? 0 : 3;  // 3 -> alloc failure (mapped to 1 below)
        }
        const int64_t row = idx - 1;  // header dropped (readData.cpp:84)
        ++total_rows;
        if (row < start) return 0;
        if (!to_end && row >= stop) return 1;  // early stop: rest unread
        if (seen == cap) {  // amortized doubling, capped at the known want
          int64_t next_cap = cap * 2;
          if (!to_end && next_cap > want) next_cap = want;
          if (static_cast<uint64_t>(next_cap) >
              SIZE_MAX / (sizeof(float) * static_cast<uint64_t>(d)))
            return 3;
          float* grown = static_cast<float*>(std::realloc(
              data, static_cast<size_t>(next_cap) * static_cast<size_t>(d) *
                        sizeof(float)));
          if (!grown) return 3;
          data = grown;
          cap = next_cap;
        }
        const int prc = parse_csv_row(lb, le, d, data + seen * d);
        if (prc) return prc;
        ++seen;
        return 0;
      });
  // Out-of-range start (or file ending inside an explicit range) is an
  // error, matching the BIN path -- a silently empty shard would hide a
  // sharding bug upstream.
  if (rc == 0 && !to_end && seen != stop - start) rc = 2;
  if (rc == 0 && to_end && start > total_rows) rc = 2;
  if (rc) {
    std::free(data);
    return rc == 3 ? 1 : rc;
  }
  *n_out = seen;
  *d_out = d;
  *data_out = data;
  return 0;
}

void gmm_free(float* p) { std::free(p); }

void* gmm_results_open(const char* path) {
  return static_cast<void*>(std::fopen(path, "w"));
}

int gmm_results_append(void* handle, const float* data, const float* memb,
                       int64_t n, int64_t d, int64_t k) {
  FILE* f = static_cast<FILE*>(handle);
  if (!f) return 1;
  // Worst-case per value: the sprintf("%f") fallback for |v| > 9e12 emits up
  // to ~47 chars for float32 extremes (3.4e38 -> 39 int digits + '.' + 6
  // decimals + sign), so budget 48 per value.
  const size_t line_cap = static_cast<size_t>(d + k) * 48 + 8;
  std::vector<char> line(line_cap);
  for (int64_t i = 0; i < n; ++i) {
    char* out = line.data();
    for (int64_t j = 0; j < d; ++j) {
      if (j) *out++ = ',';
      out = format_f(out, static_cast<double>(data[i * d + j]));
    }
    *out++ = '\t';
    for (int64_t c = 0; c < k; ++c) {
      if (c) *out++ = ',';
      out = format_f(out, static_cast<double>(memb[i * k + c]));
    }
    *out++ = '\n';
    if (std::fwrite(line.data(), 1, static_cast<size_t>(out - line.data()),
                    f) != static_cast<size_t>(out - line.data()))
      return 1;
  }
  return 0;
}

int gmm_results_close(void* handle) {
  FILE* f = static_cast<FILE*>(handle);
  if (!f) return 1;
  return std::fclose(f) == 0 ? 0 : 1;
}

int gmm_write_results(const char* path, const float* data, const float* memb,
                      int64_t n, int64_t d, int64_t k) {
  void* h = gmm_results_open(path);
  if (!h) return 1;
  const int rc = gmm_results_append(h, data, memb, n, d, k);
  const int rc2 = gmm_results_close(h);
  return rc ? rc : rc2;
}
