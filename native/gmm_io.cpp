// Native I/O runtime for the TPU GMM framework.
//
// The reference's data path is native C++ (readData.cpp); this library keeps
// that property for the TPU build: hot text parsing and result formatting run
// in C++, exposed through a minimal C ABI consumed via ctypes
// (cuda_gmm_mpi_tpu/io/native.py). Semantics match the reference readers:
//   - dispatch on a trailing "bin" in the filename (readData.cpp:28)
//   - BIN: int32 nevents, int32 ndims, float32 row-major payload
//     (readData.cpp:35-47)
//   - CSV: dims counted from the first line, FIRST LINE DROPPED as a header
//     (readData.cpp:84), blank lines skipped, atof-style field parsing
//     (strtof prefix semantics), ragged rows -> error (readData.cpp:104-107)
// and the .results writer (gaussian.cu:1042-1059): "%f" CSV of the event data,
// a tab, "%f" CSV of the per-cluster memberships, one line per event.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// rc: 0 ok, 1 open/alloc failure, 2 malformed content
int gmm_read_data(const char* path, int64_t* n_out, int64_t* d_out,
                  float** data_out);
void gmm_free(float* p);
int gmm_write_results(const char* path, const float* data, const float* memb,
                      int64_t n, int64_t d, int64_t k);
// Streaming variant: open once, append event blocks, close. Bounded memory
// for arbitrarily large N (the 10M x 128 posterior matrix never exists).
void* gmm_results_open(const char* path);
int gmm_results_append(void* handle, const float* data, const float* memb,
                       int64_t n, int64_t d, int64_t k);
int gmm_results_close(void* handle);

}  // extern "C"

namespace {

int read_bin(const char* path, int64_t* n_out, int64_t* d_out,
             float** data_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  int32_t header[2];
  if (std::fread(header, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return 2;
  }
  const int64_t n = header[0], d = header[1];
  if (n <= 0 || d <= 0) {
    std::fclose(f);
    return 2;
  }
  const size_t count = static_cast<size_t>(n) * static_cast<size_t>(d);
  float* data = static_cast<float*>(std::malloc(count * sizeof(float)));
  if (!data) {
    std::fclose(f);
    return 1;
  }
  if (std::fread(data, sizeof(float), count, f) != count) {
    std::free(data);
    std::fclose(f);
    return 2;
  }
  std::fclose(f);
  *n_out = n;
  *d_out = d;
  *data_out = data;
  return 0;
}

// Count comma-separated fields on [p, end).
int64_t count_fields(const char* p, const char* end) {
  int64_t fields = 1;
  for (; p < end; ++p)
    if (*p == ',') ++fields;
  return fields;
}

int read_csv(const char* path, int64_t* n_out, int64_t* d_out,
             float** data_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  std::fseek(f, 0, SEEK_END);
  const long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return 2;
  }
  std::fclose(f);

  // Split into non-empty lines (skip blanks, strip \r) -- readData.cpp:58-64.
  const char* p = buf.data();
  const char* const end = p + buf.size();
  std::vector<std::pair<const char*, const char*>> lines;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* stop = nl ? nl : end;
    const char* right = stop;
    while (right > p && (right[-1] == '\r')) --right;
    if (right > p) lines.emplace_back(p, right);
    p = nl ? nl + 1 : end;
  }
  if (lines.empty()) return 2;

  const int64_t d = count_fields(lines[0].first, lines[0].second);
  const int64_t n = static_cast<int64_t>(lines.size()) - 1;  // header dropped
  if (n <= 0) return 2;

  float* data = static_cast<float*>(
      std::malloc(static_cast<size_t>(n) * static_cast<size_t>(d) *
                  sizeof(float)));
  if (!data) return 1;

  for (int64_t i = 0; i < n; ++i) {
    const char* q = lines[static_cast<size_t>(i) + 1].first;
    const char* qe = lines[static_cast<size_t>(i) + 1].second;
    if (count_fields(q, qe) != d) {
      std::free(data);
      return 2;
    }
    for (int64_t j = 0; j < d; ++j) {
      // strtof prefix parse == atof semantics (readData.cpp:108); it stops at
      // the comma on its own, no per-field copies needed.
      char* next = nullptr;
      data[i * d + j] = std::strtof(q, &next);
      if (next == q) data[i * d + j] = 0.0f;  // non-numeric field -> 0.0
      const char* comma = static_cast<const char*>(
          std::memchr(q, ',', static_cast<size_t>(qe - q)));
      q = comma ? comma + 1 : qe;
    }
  }
  *n_out = n;
  *d_out = d;
  *data_out = data;
  return 0;
}

// %f formatting without printf overhead: 6 fixed decimals, round-half-away.
char* format_f(char* out, double v) {
  if (v < 0) {
    *out++ = '-';
    v = -v;
  }
  // Overflow-safe for the float32 inputs we emit (fits in int64 up to ~9e12).
  if (v > 9e12) return out + std::sprintf(out, "%f", v);
  const int64_t scaled = static_cast<int64_t>(v * 1e6 + 0.5);
  const int64_t ip = scaled / 1000000, fp = scaled % 1000000;
  out += std::sprintf(out, "%lld", static_cast<long long>(ip));
  *out++ = '.';
  for (int64_t div = 100000; div >= 1; div /= 10)
    *out++ = static_cast<char>('0' + (fp / div) % 10);
  return out;
}

}  // namespace

int gmm_read_data(const char* path, int64_t* n_out, int64_t* d_out,
                  float** data_out) {
  const size_t len = std::strlen(path);
  if (len >= 3 && std::strcmp(path + len - 3, "bin") == 0)
    return read_bin(path, n_out, d_out, data_out);
  return read_csv(path, n_out, d_out, data_out);
}

void gmm_free(float* p) { std::free(p); }

void* gmm_results_open(const char* path) {
  return static_cast<void*>(std::fopen(path, "w"));
}

int gmm_results_append(void* handle, const float* data, const float* memb,
                       int64_t n, int64_t d, int64_t k) {
  FILE* f = static_cast<FILE*>(handle);
  if (!f) return 1;
  // Worst-case per value: sign + 20 int digits + '.' + 6 decimals + sep.
  const size_t line_cap = static_cast<size_t>(d + k) * 32 + 8;
  std::vector<char> line(line_cap);
  for (int64_t i = 0; i < n; ++i) {
    char* out = line.data();
    for (int64_t j = 0; j < d; ++j) {
      if (j) *out++ = ',';
      out = format_f(out, static_cast<double>(data[i * d + j]));
    }
    *out++ = '\t';
    for (int64_t c = 0; c < k; ++c) {
      if (c) *out++ = ',';
      out = format_f(out, static_cast<double>(memb[i * k + c]));
    }
    *out++ = '\n';
    if (std::fwrite(line.data(), 1, static_cast<size_t>(out - line.data()),
                    f) != static_cast<size_t>(out - line.data()))
      return 1;
  }
  return 0;
}

int gmm_results_close(void* handle) {
  FILE* f = static_cast<FILE*>(handle);
  if (!f) return 1;
  return std::fclose(f) == 0 ? 0 : 1;
}

int gmm_write_results(const char* path, const float* data, const float* memb,
                      int64_t n, int64_t d, int64_t k) {
  void* h = gmm_results_open(path);
  if (!h) return 1;
  const int rc = gmm_results_append(h, data, memb, n, d, k);
  const int rc2 = gmm_results_close(h);
  return rc ? rc : rc2;
}
