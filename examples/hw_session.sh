#!/bin/bash
# Round-5 TPU measurement session (run when the axon tunnel is ALIVE).
#
# One-shot, resumable: each step logs to $LOGDIR/<step>.log and is skipped
# on re-run if that log ends with DONE -- the round-3 lesson (a 7h tunnel
# outage killed the measurement story) is to capture everything the moment
# the tunnel is up, most-important first, with per-step durability.
#
# Protocol notes (.claude/skills/verify/SKILL.md): generous budgets, no
# tight `timeout` wrappers (a killed mid-execution client wedges the
# single-admission tunnel), amortized timing inside each script.
# HW_SMOKE=1 shrinks every step to toy shapes on CPU so the whole runbook
# can be validated end-to-end without the tunnel (a broken step discovered
# DURING the real session wastes the tunnel window).
set -u
cd "$(dirname "$0")/.."
# The package is not pip-installed; examples/* import it from the repo root.
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
SMOKE=()
default_logdir=hw_r05_logs
if [ "${HW_SMOKE:-}" = "1" ]; then
  default_logdir=/tmp/hw_smoke_logs
  export GMM_BENCH_CPU=1
  export GMM_BENCH_MAX_N=20000
  # CPU bench runs default precompute ON (bench.py), which would make the
  # smoke bench_north identical to the bench_north_feats A/B and leave the
  # precompute-OFF path -- the one the real accelerator session runs --
  # unrehearsed. Force it off; the feats step's `env GMM_BENCH_PRECOMPUTE=1`
  # still wins for its own step, preserving the A/B shape.
  export GMM_BENCH_PRECOMPUTE=0
  SMOKE=(--n=20000 --chunk=4096 --iters=2 --device=cpu)
else
  # This session exists to measure the accelerator; if the tunnel is gone,
  # a bench step must exit 3 immediately (step() then aborts the session)
  # rather than burn hours measuring a 10M-event config on CPU. One probe
  # attempt only: retry-on-wedge is the OUTER loop's job
  # (hw_wait_and_run.sh), and bench.py's default 5-attempt ladder would
  # fire the timeout-killed-client pile-up astep() exists to avoid.
  export GMM_BENCH_REQUIRE_ACCEL=1
  export GMM_BENCH_PROBE_ATTEMPTS=${GMM_BENCH_PROBE_ATTEMPTS:-1}
fi
LOGDIR=${LOGDIR:-$default_logdir}
mkdir -p "$LOGDIR"

abort_wedged() {
  # Continuing past a dead tunnel would make every remaining step fire its
  # own ladder of timeout-killed probe clients against it -- the exact
  # pile-up SKILL.md warns extends the wedge. Stop; resume later (rc 3 is
  # also hw_wait_and_run.sh's signal to go back to waiting).
  echo "== $1: accelerator unavailable -- aborting session;"
  echo "   re-run examples/hw_session.sh when the tunnel returns"
  exit 3
}

finish_step() {  # finish_step <name> <log> <rc>
  if [ "$3" -eq 0 ]; then
    echo DONE | tee -a "$2"
  elif [ "$3" -eq 3 ]; then
    abort_wedged "$1"   # bench.py contract: probe fallback or watchdog
  else
    echo "== $1: failed (rc=$3); no DONE written, will re-run on resume"
  fi
}

skip_done() {  # true (and prints) if this step's log already ends in DONE
  [ -f "$1" ] && grep -q "^DONE$" "$1"
}

settle() {
  # Let the single-admission relay release the previous client before the
  # next one connects. Observed 2026-07-31: a step that connected ~6s
  # after the prior client exited hung forever in device init (in-process
  # init has no retry) and its watchdog-kill then wedged the tunnel for
  # the rest of the window; the same relay had just served back-to-back
  # clients spaced ~25s apart without trouble. Applies before the FIRST
  # step too: the documented entry path probes the tunnel immediately
  # before launching this script, and that probe was itself a client.
  [ ${#SMOKE[@]} -eq 0 ] && sleep "${HW_STEP_SETTLE_S:-45}"
  return 0
}

# For bench.py, which carries its own accelerator probe, CPU-fallback
# refusal (GMM_BENCH_REQUIRE_ACCEL) and mid-run watchdog.
step() {
  local name=$1; shift
  local log="$LOGDIR/$name.log"
  if skip_done "$log"; then echo "== $name: already done, skipping"; return 0; fi
  settle
  echo "== $name: $*"
  "$@" 2>&1 | tee "$log"
  finish_step "$name" "$log" "${PIPESTATUS[0]}"
}

# For the example scripts, which have NO probe/watchdog of their own: a
# wedged tunnel would hang their in-process device init forever. Guard
# with a single preflight probe client (no retry ladder) and an outer
# wall-clock bound; either failing aborts the session. The outer timeout
# is the lesser evil explicitly: yes, a timeout-killed client can extend
# the wedge (SKILL.md), but we abort right after, so nothing piles up --
# whereas an unbounded hang silently eats the whole unattended window.
astep() {
  local name=$1; shift
  local log="$LOGDIR/$name.log"
  if skip_done "$log"; then echo "== $name: already done, skipping"; return 0; fi
  settle
  if [ ${#SMOKE[@]} -eq 0 ]; then
    if ! timeout 180 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      abort_wedged "$name (preflight probe)"
    fi
    sleep "${GMM_BENCH_SETTLE_S:-10}"   # probe client was just admitted
    echo "== $name: $*"
    timeout "${HW_STEP_TIMEOUT_S:-3600}" "$@" 2>&1 | tee "$log"
    local rc=${PIPESTATUS[0]}
    [ "$rc" -eq 124 ] && abort_wedged "$name (exceeded ${HW_STEP_TIMEOUT_S:-3600}s)"
    finish_step "$name" "$log" "$rc"
  else
    echo "== $name: $*"
    "$@" 2>&1 | tee "$log"
    finish_step "$name" "$log" "${PIPESTATUS[0]}"
  fi
}

# Step order = VERDICT r4 priority, because observed windows die
# mid-session (round 4: ONE step completed): the official BENCH artifact
# first, then the two inputs to the routing decision (feature hoist,
# kernel decision rows), then the MFU decomposition, then the config
# matrix (incl. the clean config-5 same-session CPU denominator), then
# the secondary A/Bs and streaming/envelope characterization.
# 1. The official bench (BENCH_r05 rehearsal): north-star on TPU.
step bench_north python bench.py
# 2. Routing decision data: feature hoist A/B + kernel-vs-XLA rows (the
#    ~5.6 ms/iter xouter HBM win).
step bench_north_feats env GMM_BENCH_PRECOMPUTE=1 python bench.py
astep kernel_north python examples/bench_kernel_precision.py north --blocks=256,512,1024 "${SMOKE[@]}"
# 3. MFU decomposition: attribute the north-star iteration's wall time to
#    quad/lse/moments/xouter components.
astep components_north python examples/bench_components.py north "${SMOKE[@]}"
# 4. Config matrix incl. 5 (fresh same-session CPU denominator rides in
#    bench.py's in-process baseline) and the reference envelope 6.
step bench_5 python bench.py --config=5
step bench_5stream python bench.py --config=5stream
step bench_6 python bench.py --config=6
step bench_3_diag python bench.py --config=3
# 5. Secondary A/Bs and characterization.
step bench_north_chunk262k env GMM_BENCH_CHUNK=262144 python bench.py
astep kernel_envelope_diag python examples/bench_kernel_precision.py envelope diag --blocks=256,512 "${SMOKE[@]}"
# 6. Streaming overlap: double-buffered out-of-core vs in-memory.
#    (SMOKE's flags come last, so they win over the full-shape defaults.)
astep stream_overlap python examples/bench_streaming.py --n=4000000 --iters=10 "${SMOKE[@]}"
astep components_envelope python examples/bench_components.py envelope --iters=10 "${SMOKE[@]}"
echo "session complete; logs in $LOGDIR/"
# Leave the decision artifact next to the logs immediately: if the window
# fired unattended, the routing analysis must not depend on someone
# remembering to run the analyzer later. Analyzer failure must be loud --
# an ANALYSIS.md that is just an error message defeats the point.
if python examples/analyze_hw_session.py "$LOGDIR" > "$LOGDIR/ANALYSIS.md" 2>&1; then
  echo "analysis written to $LOGDIR/ANALYSIS.md"
else
  echo "ERROR: analyze_hw_session.py failed (rc=$?); $LOGDIR/ANALYSIS.md holds its output"
  exit 4   # distinct from 3 (wedge): data captured, analysis broken
fi
