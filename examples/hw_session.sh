#!/bin/bash
# Round-4 TPU measurement session (run when the axon tunnel is ALIVE).
#
# One-shot, resumable: each step logs to $LOGDIR/<step>.log and is skipped
# on re-run if that log ends with DONE -- the round-3 lesson (a 7h tunnel
# outage killed the measurement story) is to capture everything the moment
# the tunnel is up, most-important first, with per-step durability.
#
# Protocol notes (.claude/skills/verify/SKILL.md): generous budgets, no
# tight `timeout` wrappers (a killed mid-execution client wedges the
# single-admission tunnel), amortized timing inside each script.
# HW_SMOKE=1 shrinks every step to toy shapes on CPU so the whole runbook
# can be validated end-to-end without the tunnel (a broken step discovered
# DURING the real session wastes the tunnel window).
set -u
cd "$(dirname "$0")/.."
# The package is not pip-installed; examples/* import it from the repo root.
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
SMOKE=()
default_logdir=hw_r04_logs
if [ "${HW_SMOKE:-}" = "1" ]; then
  default_logdir=/tmp/hw_smoke_logs
  export GMM_BENCH_CPU=1
  SMOKE=(--n=20000 --chunk=4096 --iters=2 --device=cpu)
fi
LOGDIR=${LOGDIR:-$default_logdir}
mkdir -p "$LOGDIR"

step() {
  local name=$1; shift
  local log="$LOGDIR/$name.log"
  if [ -f "$log" ] && grep -q "^DONE$" "$log"; then
    echo "== $name: already done, skipping"
    return 0
  fi
  echo "== $name: $*"
  { "$@" && echo DONE; } 2>&1 | tee "$log"
}

# 1. The official bench (BENCH_r04 rehearsal): north-star on TPU; plus the
#    two one-env A/Bs (feature hoist; double-size chunk tile).
step bench_north python bench.py
step bench_north_feats env GMM_BENCH_PRECOMPUTE=1 python bench.py
step bench_north_chunk262k env GMM_BENCH_CHUNK=262144 python bench.py
# 2. Kernel-vs-XLA(-vs-feature-hoist) decision data (the ~5.6 ms/iter
#    xouter HBM win).
step kernel_north python examples/bench_kernel_precision.py north --blocks=256,512,1024 "${SMOKE[@]}"
step kernel_envelope_diag python examples/bench_kernel_precision.py envelope diag --blocks=256,512 "${SMOKE[@]}"
# 3. Config matrix incl. 5 (fresh same-session CPU denominator rides in
#    bench.py's in-process baseline) and the reference envelope 6.
step bench_5 python bench.py --config=5
step bench_5stream python bench.py --config=5stream
step bench_6 python bench.py --config=6
step bench_3_diag python bench.py --config=3
# 4. Streaming overlap: double-buffered out-of-core vs in-memory (item 6).
#    (SMOKE's flags come last, so they win over the full-shape defaults.)
step stream_overlap python examples/bench_streaming.py --n=4000000 --iters=10 "${SMOKE[@]}"
# 5. MFU decomposition (item 3): attribute the north-star iteration's
#    wall time to quad/lse/moments/xouter components.
step components_north python examples/bench_components.py north "${SMOKE[@]}"
step components_envelope python examples/bench_components.py envelope --iters=10 "${SMOKE[@]}"
echo "session complete; logs in $LOGDIR/"
