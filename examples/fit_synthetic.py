"""End-to-end example: fit a GMM with model-order search on synthetic data.

Generates a well-separated mixture, fits from K=12 down with the Rissanen
search, and prints the recovered structure. Runs on whatever platform JAX
picks (CPU works; on TPU the XLA fused path is the measured default --
`use_pallas='always'` selects the hand-written kernel, docs/PERF.md).

  PYTHONPATH=. python examples/fit_synthetic.py [--device=cpu]
"""

import sys

import numpy as np

from cuda_gmm_mpi_tpu import GaussianMixture


def main() -> int:
    device = None
    for a in sys.argv[1:]:
        if a.startswith("--device="):
            device = a.split("=", 1)[1]

    rng = np.random.default_rng(0)
    true_k, d = 5, 8
    centers = rng.normal(scale=12.0, size=(true_k, d))
    labels = rng.integers(0, true_k, size=50_000)
    data = (centers[labels] + rng.normal(size=(50_000, d))).astype(np.float32)

    gm = GaussianMixture(
        12,                      # start high; the merge search reduces K
        min_iters=25, max_iters=25, chunk_size=8192, device=device,
    ).fit(data)

    print(f"selected K = {gm.n_components_} (true {true_k})")
    print(f"rissanen   = {gm.rissanen_:.2f}")
    print(f"mean loglik/event = {gm.score(data):.4f}")
    dists = np.linalg.norm(
        gm.means_[:, None, :] - centers[None, :, :], axis=2
    ).min(axis=0)
    print("distance from each true center to nearest recovered mean:")
    print("  " + " ".join(f"{v:.3f}" for v in dists))
    return 0


if __name__ == "__main__":
    sys.exit(main())
