"""Streaming (out-of-core) EM throughput vs the in-memory path.

VERDICT r3 item 6's acceptance measurement: with double-buffered
host->device block transfers (models/streaming.py), a device-resident-able
N should stream within ~1.3x of the in-memory path's wall time -- the
remaining gap is the irreducible host dispatch per block plus whatever
copy time the compute fails to hide.

Usage: python examples/bench_streaming.py [--n=4000000] [--d=24] [--k=64]
           [--iters=10] [--chunk=131072] [--mesh=N]
Prints one line per path; in-memory first (it also warms the data gen).
"""

from __future__ import annotations

import sys
import time

import numpy as np

from _bench_data import make_bench_data


def main() -> int:
    n, d, k, iters, chunk, mesh = 4_000_000, 24, 64, 10, 131072, 0
    for a in sys.argv[1:]:
        key, _, val = a.partition("=")
        if key == "--n":
            n = int(val)
        elif key == "--d":
            d = int(val)
        elif key == "--k":
            k = int(val)
        elif key == "--iters":
            iters = int(val)
        elif key == "--chunk":
            chunk = int(val)
        elif key == "--mesh":
            mesh = int(val)

    import jax

    for a in sys.argv[1:]:
        if a.startswith("--device="):
            # Env JAX_PLATFORMS is not authoritative on this image
            # (sitecustomize re-pins it); config.update is.
            jax.config.update("jax_platforms", a.split("=", 1)[1])
    import jax.numpy as jnp

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.models.streaming import StreamingGMMModel
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    print(f"platform: {jax.devices()[0].platform}  n={n} d={d} k={k} "
          f"iters={iters} chunk={chunk} mesh={mesh or 'off'}", flush=True)

    data, _ = make_bench_data(n, d, k)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(n, d)
    mesh_shape = (mesh, 1) if mesh else None

    def timed(tag, model, chunks, wts):
        s, ll, _ = model.run_em(state, chunks, wts, eps,
                                min_iters=1, max_iters=1)
        jax.block_until_ready(s)
        times = []
        for r in range(3):
            sr = state.replace(means=state.means * (1.0 + 1e-6 * (r + 1)))
            t0 = time.perf_counter()
            s, ll_dev, it = model.run_em(sr, chunks, wts, eps,
                                         min_iters=iters, max_iters=iters)
            ll = float(ll_dev)
            times.append(time.perf_counter() - t0)
        dt = min(times) / int(it)
        print(f"{tag:22s} {dt*1e3:8.2f} ms/iter  loglik={ll:.0f}",
              flush=True)
        return dt

    # In-memory reference (sharded when --mesh is set, plain otherwise).
    if mesh_shape:
        from cuda_gmm_mpi_tpu.parallel import ShardedGMMModel

        m = ShardedGMMModel(GMMConfig(chunk_size=chunk,
                                      mesh_shape=mesh_shape))
        c_np, w_np = chunk_events(data, chunk, m.data_size)
        st, c, w = m.prepare(state, c_np, w_np)
        dt_mem = timed("in-memory sharded", m, c, w)
    else:
        m = GMMModel(GMMConfig(chunk_size=chunk))
        c_np, w_np = chunk_events(data, chunk)
        dt_mem = timed("in-memory", m, jnp.asarray(c_np), jnp.asarray(w_np))

    sm = StreamingGMMModel(GMMConfig(chunk_size=chunk,
                                     stream_events=True,
                                     mesh_shape=mesh_shape))
    c_np, w_np = chunk_events(data, chunk, sm.data_size)
    st, c, w = sm.prepare(state, c_np, w_np)
    dt_str = timed("streaming", sm, c, w)
    print(f"streaming/in-memory ratio: {dt_str / dt_mem:.2f}x", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
