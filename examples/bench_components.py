"""Decompose one fused E+M iteration into its component costs on TPU.

The MFU-push tool (VERDICT r4 item 3): after the kernel-vs-XLA decision,
this attributes the north-star iteration's wall time to its pieces so the
next bottleneck is measured, not guessed:

  full     -- the complete fused chunk_stats pass (what bench.py times)
  quad     -- xouter features + the (B,F)@(F,K) + (B,D)@(D,K) logp matmuls
  estep    -- the full posteriors() pass (quad + masked LSE + softmax);
              estep - quad ~ the VPU-bound LSE/softmax cost
  moments  -- the (K,B)@(B,D) M1 and (K,B)@(B,F) M2 accumulations
  xouter   -- materializing the [B,F] outer-product features alone
              (optimization_barrier forces the materialization XLA would
              otherwise fuse away)

Components overlap (quad+lse+moments ~ full, minus fusion wins), so read
the deltas, not the absolute sum. Timing protocol per the verify-skill
runbook: every component is a lax.scan over the chunk grid inside ONE jit
(amortizes the tunnel's per-dispatch latency), min-of-3 perturbed reps,
readback inside the timed region.

Usage: python examples/bench_components.py [north|envelope] [--iters=20]
           [--precision=high] [--device=cpu]
"""

from __future__ import annotations

import sys
import time

import numpy as np

from _bench_data import make_bench_data

SHAPES = {
    "north": dict(n=1_000_000, d=24, k=100),
    "envelope": dict(n=1_000_000, d=32, k=512),
}


def main() -> int:
    names = [a for a in sys.argv[1:] if not a.startswith("--")] or ["north"]
    iters, precision = 20, "high"
    n_override, chunk = None, 131072
    for a in sys.argv[1:]:
        if a.startswith("--iters="):
            iters = int(a.split("=", 1)[1])
        if a.startswith("--precision="):
            precision = a.split("=", 1)[1]
        if a.startswith("--n="):  # smoke-testing the runbook off-TPU
            n_override = int(a.split("=", 1)[1])
        if a.startswith("--chunk="):
            chunk = int(a.split("=", 1)[1])

    import jax

    for a in sys.argv[1:]:
        if a.startswith("--device="):
            jax.config.update("jax_platforms", a.split("=", 1)[1])

    import jax.numpy as jnp
    from jax import lax

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import chunk_events
    from cuda_gmm_mpi_tpu.ops.estep import posteriors
    from cuda_gmm_mpi_tpu.ops.mstep import chunk_stats
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    print(f"platform: {jax.devices()[0].platform}  precision={precision} "
          f"iters={iters}", flush=True)
    prec = {"default": lax.Precision.DEFAULT, "high": lax.Precision.HIGH,
            "highest": lax.Precision.HIGHEST}[precision]

    for name in names:
        spec = SHAPES[name]
        n, d, k = spec["n"], spec["d"], spec["k"]
        if n_override:
            n = n_override
        data, _ = make_bench_data(n, d, k)
        state = seed_clusters_host(data, k)
        chunks_np, wts_np = chunk_events(data, chunk)
        chunks, wts = jnp.asarray(chunks_np), jnp.asarray(wts_np)
        kw = dict(diag_only=False, quad_mode="expanded",
                  matmul_precision=precision)

        def scan_over_chunks(per_chunk):
            """ONE jit covering all ``iters`` repetitions: an outer scan
            whose carry perturbs the state per repetition (sequential
            dependence -- no layer can CSE or parallelize the reps) around
            an inner scan over the chunk grid. Amortizes the remote
            tunnel's per-dispatch latency per the verify-skill runbook."""
            def f(st, ch, wt):
                def iter_body(c, _):
                    st2 = st.replace(means=st.means * (1.0 + c * 1e-12))

                    def body(cc, xw):
                        x, w_row = xw
                        return cc + per_chunk(st2, x, w_row), None

                    out, _ = lax.scan(body, c * 1e-30, (ch, wt))
                    return out, None
                tot, _ = lax.scan(iter_body, jnp.float32(0.0), None,
                                  length=iters)
                return tot
            return jax.jit(f)

        def full_chunk(st, x, w_row):
            s = chunk_stats(st, x, w_row, **kw)
            return s.loglik.astype(jnp.float32) + jnp.sum(s.M2) * 0

        def quad_chunk(st, x, w_row):
            B, D = x.shape
            xo = (x[:, :, None] * x[:, None, :]).reshape(B, D * D)
            A = st.Rinv.reshape(k, D * D)
            b = jnp.einsum("kde,ke->kd", st.Rinv, st.means, precision=prec)
            q = (jnp.matmul(xo, A.T, precision=prec)
                 - 2.0 * jnp.matmul(x, b.T, precision=prec))
            return jnp.sum(q * 1e-9) + jnp.sum(w_row) * 0

        def estep_chunk(st, x, w_row):
            # the whole E-side: logp matmuls -> LSE -> softmax (no moments)
            w, logZ = posteriors(st, x, **kw)
            return jnp.sum(logZ) + jnp.sum(w[:, :1]) * 0

        def moments_chunk(st, x, w_row):
            B, D = x.shape
            xo = (x[:, :, None] * x[:, None, :]).reshape(B, D * D)
            w = jnp.broadcast_to(w_row[:, None], (B, k)) * 1e-6
            M1 = jnp.einsum("nk,nd->kd", w, x, precision=prec)
            M2 = jnp.einsum("nk,nf->kf", w, xo, precision=prec)
            return jnp.sum(M1) + jnp.sum(M2) * 1e-9

        def xouter_chunk(st, x, w_row):
            B, D = x.shape
            xo = (x[:, :, None] * x[:, None, :]).reshape(B, D * D)
            # Barrier: without it XLA fuses the strided sum into the
            # producer and never materializes the [B, F] tensor -- the
            # exact cost this component exists to measure.
            xo = lax.optimization_barrier(xo)
            return jnp.sum(xo[:, ::7]) * 1e-9 + jnp.sum(w_row) * 0

        comps = [("full", full_chunk), ("quad", quad_chunk),
                 ("estep", estep_chunk), ("moments", moments_chunk),
                 ("xouter", xouter_chunk)]
        for tag, per_chunk in comps:
            fn = scan_over_chunks(per_chunk)
            # warm/compile
            float(fn(state, chunks, wts))
            times = []
            for r in range(3):
                sr = state.replace(
                    means=state.means * (1.0 + 1e-6 * (r + 1)))
                t0 = time.perf_counter()
                v = float(fn(sr, chunks, wts))
                times.append((time.perf_counter() - t0) / iters)
            assert np.isfinite(v)
            print(f"{name:9s} {tag:8s} {min(times)*1e3:8.2f} ms/pass",
                  flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
