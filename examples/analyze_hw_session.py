"""Turn a captured hw_session.sh log directory into the routing decision.

The TPU tunnel's windows are short and unpredictable (round-3: one 7h
outage; round-4: one bench captured before a wedge), so the measurement
session only CAPTURES data; the analysis — which backend should
`use_pallas='auto'` route per shape, whether `precompute_features` should
default on, what the chunk-tile A/B said — happens offline from the logs,
whenever. This script is that analysis.

Usage: python examples/analyze_hw_session.py [logdir]   (default hw_r05_logs)

Reads:
  kernel_*.log        -- bench_kernel_precision.py rows:
                         "<shape> <tag> <ms> ms/iter loglik=<ll>"
  bench_*.log         -- bench.py JSON lines (north + A/Bs + config matrix)
  components_*.log    -- bench_components.py rows ("<shape> <comp> <ms>
                         ms/pass"): the MFU decomposition
  stream_overlap.log  -- bench_streaming.py ("streaming/in-memory ratio")
Prints a markdown decision table (paste into docs/PERF.md) plus the
per-shape winner and the code changes it implies. Purely textual: no jax,
no devices, safe to run anywhere.
"""

from __future__ import annotations

import json
import os
import re
import sys

ROW = re.compile(
    r"^(?P<shape>\w+)\s+(?P<tag>(?:xla\+feats|xla|kernel)\b.*?)\s+"
    r"(?P<ms>[0-9.]+)\s+ms/iter\s+loglik=(?P<ll>-?[0-9.]+)")
FAIL = re.compile(r"^(?P<shape>\w+)\s+(?P<tag>kernel [^:]+): FAILED (?P<err>.*)")


def _log_lines(logdir, prefix):
    """(filename_stem, stripped_line) for every line of {prefix}*.log."""
    for fn in sorted(os.listdir(logdir)):
        if not (fn.startswith(prefix) and fn.endswith(".log")):
            continue
        with open(os.path.join(logdir, fn)) as fh:
            for line in fh:
                yield fn[:-4], line.strip()


def parse_kernel_logs(logdir):
    rows, fails = [], []
    for _, line in _log_lines(logdir, "kernel"):
        m = ROW.match(line)
        if m:
            rows.append(dict(shape=m["shape"], tag=m["tag"].strip(),
                             ms=float(m["ms"]), loglik=float(m["ll"])))
            continue
        f = FAIL.match(line)
        if f:
            fails.append(dict(shape=f["shape"], tag=f["tag"],
                              err=f["err"].strip()))
    return rows, fails


def parse_bench_logs(logdir):
    out = {}
    for stem, line in _log_lines(logdir, "bench"):
        if line.startswith("{"):
            try:
                out[stem] = json.loads(line)
            except ValueError:
                pass
    return out


COMPONENT_ROW = re.compile(
    r"^(?P<shape>\w+)\s+(?P<comp>\w+)\s+(?P<ms>[0-9.]+)\s+ms/pass")
# '--mesh' runs tag their reference row 'in-memory sharded'
# (bench_streaming.py); missing that variant left the loglik pair
# unparsed, permanently reporting "answer agreement unverified".
STREAM_ROW = re.compile(
    r"^(?P<mode>in-memory(?: sharded)?|streaming)\s+(?P<ms>[0-9.]+)\s+"
    r"ms/iter\s+loglik=(?P<ll>-?[0-9.]+)")
STREAM_RATIO = re.compile(
    r"^streaming/in-memory ratio:\s*(?P<ratio>[0-9.]+)x")


def parse_component_logs(logdir):
    """[(shape, component, ms)] from components_*.log (bench_components.py)."""
    rows = []
    for _, line in _log_lines(logdir, "components"):
        m = COMPONENT_ROW.match(line)
        if m:
            rows.append((m["shape"], m["comp"], float(m["ms"])))
    return rows


def parse_stream_overlap(logdir):
    """(wall ratio, loglik drift) from stream_overlap.log, or None.

    Drift is |streaming - in-memory| / max(1, |in-memory|): a fast
    streaming path that computed a DIFFERENT answer must be flagged, not
    celebrated (same guard the kernel decision table applies)."""
    ratio, lls = None, {}
    for stem, line in _log_lines(logdir, "stream_overlap"):
        if stem != "stream_overlap":
            # Exactly one run's file: merging fields across e.g. a
            # stream_overlap_mesh8.log variant would compute drift between
            # two different runs.
            continue
        m = STREAM_RATIO.match(line)
        if m:
            ratio = float(m["ratio"])
        m = STREAM_ROW.match(line)
        if m:
            # Normalize 'in-memory sharded' onto the plain key: either
            # variant is THE in-memory reference of its run.
            mode = ("in-memory" if m["mode"].startswith("in-memory")
                    else m["mode"])
            lls[mode] = float(m["ll"])
    if ratio is None:
        return None
    drift = None
    if "in-memory" in lls and "streaming" in lls:
        drift = (abs(lls["streaming"] - lls["in-memory"])
                 / max(1.0, abs(lls["in-memory"])))
    return ratio, drift


def precision_of(tag):
    for p in ("highest", "high", "default"):
        if f" {p}" in " " + tag.replace("b=", "").replace("+feats", ""):
            return p
    return "?"


def backend_of(tag):
    if tag.startswith("xla+feats"):
        return "xla+feats"
    if tag.startswith("kernel"):
        return "kernel"
    return "xla"


def main() -> int:
    logdir = sys.argv[1] if len(sys.argv) > 1 else "hw_r05_logs"
    if not os.path.isdir(logdir):
        print(f"analyze_hw_session: no such logdir {logdir!r}", file=sys.stderr)
        return 2
    rows, fails = parse_kernel_logs(logdir)
    bench = parse_bench_logs(logdir)

    if rows:
        # Decision table: per (shape, precision), every measured backend,
        # winner marked. loglik column guards against a "win" that computed
        # a different answer (all backends run the same EM; logliks must
        # agree to ~1e-4 relative).
        print("## Kernel-vs-XLA decision table\n")
        print("| shape | precision | backend | ms/iter | vs best | loglik |")
        print("|---|---|---|---|---|---|")
        decisions = {}
        shapes = sorted({r["shape"] for r in rows})
        for shape in shapes:
            for prec in ("high", "highest", "default"):
                grp = [r for r in rows
                       if r["shape"] == shape and precision_of(r["tag"]) == prec]
                if not grp:
                    continue
                # Answer-correctness reference: the plain XLA row (the path
                # the whole test suite oracles against sklearn), falling
                # back to the group median. NOT the speed winner's own
                # loglik -- a fastest-but-wrong backend must lose, not
                # become the yardstick everyone else "drifts" from.
                xla = [r for r in grp if backend_of(r["tag"]) == "xla"]
                if xla:
                    ll0 = xla[0]["loglik"]
                else:
                    lls = sorted(r["loglik"] for r in grp)
                    ll0 = lls[len(lls) // 2]

                def drifted(r):
                    return abs(r["loglik"] - ll0) / max(1.0, abs(ll0)) > 1e-4

                sound = [r for r in grp if not drifted(r)]
                best = min(sound or grp, key=lambda r: r["ms"])
                for r in sorted(grp, key=lambda r: r["ms"]):
                    mark = " **<- winner**" if r is best else ""
                    warn = " (ANSWER DRIFT, excluded)" if drifted(r) else ""
                    print(f"| {shape} | {prec} | {r['tag']}{mark} | "
                          f"{r['ms']:.2f} | {r['ms']/best['ms']:.2f}x | "
                          f"{r['loglik']:.0f}{warn} |")
                decisions[(shape, prec)] = best
        print()
        print("### Routing implied (for ops/pallas should_use_pallas + "
              "GMMConfig.precompute_features defaults)\n")
        for (shape, prec), best in sorted(decisions.items()):
            b = backend_of(best["tag"])
            extra = ""
            if b == "kernel":
                bb = re.search(r"b=(\d+)", best["tag"])
                extra = f" (pallas_block_b={bb.group(1)})" if bb else ""
            if b == "xla+feats":
                extra = " (precompute_features=True)"
            print(f"- {shape} @ {prec}: route to **{b}**{extra}")
        print()
    if fails:
        print("### Kernel compile failures (decision data too)\n")
        for f in fails:
            print(f"- {f['shape']} {f['tag']}: {f['err']}")
        print()

    if bench:
        print("## bench.py captures\n")
        print("| run | iters/sec | ms/iter | vs CPU | note |")
        print("|---|---|---|---|---|")
        for name, j in sorted(bench.items()):
            if j.get("accelerator_unavailable"):
                note = "NO MEASUREMENT (tunnel down)"
                print(f"| {name} | - | - | - | {note} |")
                continue
            ms = j.get("wall_s_per_iter", 0) * 1e3
            print(f"| {name} | {j['value']:.1f} | {ms:.1f} | "
                  f"{j['vs_baseline']:.0f}x | {j.get('precision', '')} |")
        print()
        # The two one-env A/Bs ride the same config as bench_north; call
        # the deltas out explicitly when all sides exist and measured.
        base = bench.get("bench_north")
        ok = lambda j: j and not j.get("accelerator_unavailable")
        if ok(base):
            for ab, label in (("bench_north_feats", "feature hoist"),
                              ("bench_north_chunk262k", "262k chunk tile")):
                j = bench.get(ab)
                if ok(j):
                    d = (j["value"] / base["value"] - 1.0) * 100
                    print(f"- {label}: {d:+.1f}% vs bench_north "
                          f"(same session)")
        print()
    comps = parse_component_logs(logdir)
    if comps:
        # MFU attribution: each component pass is timed standalone, so the
        # 'full' row is the yardstick and the parts may not sum to it
        # (XLA fuses differently in the full program -- that residual IS
        # decision data: a large one means the standalone timings
        # misattribute and only a trace can split further).
        print("## Component decomposition (ms/pass, standalone)\n")
        print("| shape | component | ms/pass | share of full |")
        print("|---|---|---|---|")
        for shape in sorted({s for s, _, _ in comps}):
            grp = [(c, ms) for s, c, ms in comps if s == shape]
            full = dict(grp).get("full")
            for c, ms in grp:
                share = f"{ms / full:.0%}" if full else "-"
                print(f"| {shape} | {c} | {ms:.2f} | {share} |")
        print()
    stream = parse_stream_overlap(logdir)
    if stream is not None:
        ratio, drift = stream
        if drift is None:
            # Ratio present but the per-mode loglik pair didn't parse: the
            # answer agreement is UNVERIFIED, which must not read as a pass.
            verdict_s = ("loglik pair unparsed -- answer agreement "
                         "unverified, treat the ratio as provisional")
        elif drift > 1e-4:
            verdict_s = (f"ANSWER DRIFT (loglik rel. diff {drift:.1e}) -- "
                         "the streaming path computed a different answer; "
                         "the ratio is void until that is fixed")
        elif ratio <= 1.3:
            verdict_s = "overlap holds (within the ~1.3x in-memory budget)"
        else:
            verdict_s = ("overlap NOT holding -- double-buffering is not "
                         "hiding host->device copies at this shape")
        print(f"## Streaming overlap\n\n- out-of-core / in-memory wall "
              f"ratio: **{ratio:.2f}x** -- {verdict_s}\n")
    if not rows and not fails and not bench and not comps and stream is None:
        print(f"analyze_hw_session: nothing parseable in {logdir}/")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
