"""Measure the Pallas kernel (incl. manual bf16_3x) vs the XLA path on TPU.

Round-3 follow-up to docs/PERF.md's precision study: the kernel now supports
'high' via the manual 3-dot decomposition (ops/pallas/fused_stats.py _kdot)
and natural operand layouts. This script produces the decision data for
whether `use_pallas='auto'` should route any config to the kernel.

Usage:  python examples/bench_kernel_precision.py [north|envelope|diag] ...
Prints one line per (backend, precision) combination; add block_b values
with --blocks=256,512,1024.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from _bench_data import make_bench_data


SHAPES = {
    "north": dict(n=1_000_000, d=24, k=100, diag=False),
    "envelope": dict(n=1_000_000, d=32, k=512, diag=False),
    "diag": dict(n=1_000_000, d=24, k=256, diag=True),
}


def main() -> int:
    names = [a for a in sys.argv[1:] if not a.startswith("--")] or ["north"]
    blocks = [512]
    iters = 20
    n_override = chunk = None
    for a in sys.argv[1:]:
        if a.startswith("--blocks="):
            blocks = [int(v) for v in a.split("=", 1)[1].split(",")]
        if a.startswith("--iters="):
            iters = int(a.split("=", 1)[1])
        if a.startswith("--n="):
            # Shrink the event count (smoke-testing the runbook off-TPU;
            # decision runs use the real shapes).
            n_override = int(a.split("=", 1)[1])
        if a.startswith("--chunk="):
            chunk = int(a.split("=", 1)[1])

    import jax

    for a in sys.argv[1:]:
        if a.startswith("--device="):
            jax.config.update("jax_platforms", a.split("=", 1)[1])
    import jax.numpy as jnp

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    print(f"platform: {jax.devices()[0].platform}", flush=True)

    for name in names:
        spec = SHAPES[name]
        n, d, k, diag = spec["n"], spec["d"], spec["k"], spec["diag"]
        if n_override:
            n = n_override
        chunk_size = chunk or 131072
        data, _ = make_bench_data(n, d, k)
        state = seed_clusters_host(data, k)
        eps = convergence_epsilon(n, d)

        def run(tag, cfg, stats_fn=None):
            model = GMMModel(cfg, stats_fn=stats_fn)
            chunks, wts = chunk_events(data, cfg.chunk_size)
            chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
            s, ll, _ = model.run_em(state, chunks, wts, eps,
                                    min_iters=1, max_iters=1)
            jax.block_until_ready(s)
            times = []
            for r in range(3):
                sr = state.replace(means=state.means * (1.0 + 1e-6 * (r + 1)))
                t0 = time.perf_counter()
                s, ll_dev, it = model.run_em(sr, chunks, wts, eps)
                ll = float(ll_dev)
                times.append(time.perf_counter() - t0)
            dt = min(times) / int(it)
            print(f"{name:9s} {tag:26s} {dt*1e3:8.2f} ms/iter  "
                  f"loglik={ll:.0f}", flush=True)

        for prec in ("high", "highest", "default"):
            cfg = GMMConfig(min_iters=iters, max_iters=iters,
                            chunk_size=chunk_size, diag_only=diag,
                            matmul_precision=prec)
            run(f"xla {prec}", cfg)
            if not diag:
                # The round-4 XLA-path candidate: features hoisted out of
                # the EM loop (precompute_features) -- kills the
                # per-iteration xouter rebuild/write at the cost of N*F*4
                # bytes HBM residency. Compare directly against the kernel
                # rows below.
                run(f"xla+feats {prec}",
                    GMMConfig(min_iters=iters, max_iters=iters,
                              chunk_size=chunk_size, diag_only=diag,
                              matmul_precision=prec,
                              precompute_features=True))
            for bb in blocks:
                # use_pallas='always' routes GMMModel through make_stats_fn,
                # which builds the kernel partial (incl. the off-TPU
                # interpret fallback) -- one policy, no duplicate here.
                kcfg = GMMConfig(min_iters=iters, max_iters=iters,
                                 chunk_size=chunk_size, diag_only=diag,
                                 matmul_precision=prec, use_pallas="always",
                                 pallas_block_b=bb)
                try:
                    run(f"kernel {prec} b={bb}", kcfg)
                except Exception as e:  # Mosaic compile failures are data too
                    print(f"{name:9s} kernel {prec} b={bb}: FAILED "
                          f"{type(e).__name__}: {str(e)[:120]}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
