"""Shared synthetic dataset for the benchmark example scripts.

One definition so bench_kernel_precision / bench_components /
bench_streaming rows measured at the same (n, d, k) are measured on the
SAME bytes -- cross-script comparisons depend on it.
"""

from __future__ import annotations

import numpy as np


def make_bench_data(n: int, d: int, k: int, seed: int = 42):
    """(data [n, d] float32, centers [k, d]): k scale-8 blobs, unit noise."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(size=(n, d))).astype(np.float32)
    return data, centers
