#!/bin/bash
# Wait for the axon TPU tunnel to come back, then run the measurement
# session (examples/hw_session.sh, resumable). Designed to run unattended
# in the background for hours.
#
# Probe discipline (.claude/skills/verify/SKILL.md): the relay is a LOCAL
# listener, so `ss -tln` is a FREE check (no tunnel client is created) —
# poll that often. A real `jax.devices()` probe creates a client, and a
# timeout-killed client can EXTEND a wedge — so only probe when the
# listener looks alive, at most once per GMM_HW_PROBE_EVERY_S (default
# 20 min), and give each probe a generous 300s.
#
# The machine should also be QUIET before the session starts: bench.py
# measures an in-process CPU baseline, and a concurrent test-suite run
# contaminated round-3's config-5 denominator. But tunnel windows are rare
# and short, and host load does not affect the TPU timings themselves, so
# a busy machine only HOLDS the launch for GMM_HW_BUSY_GRACE_S (default
# 600s); after that the session launches anyway and the CPU-baseline
# contamination risk is logged.
set -u
cd "$(dirname "$0")/.."
PROBE_EVERY_S=${GMM_HW_PROBE_EVERY_S:-1200}
POLL_S=${GMM_HW_POLL_S:-120}
DEADLINE_S=${GMM_HW_DEADLINE_S:-36000}
BUSY_GRACE_S=${GMM_HW_BUSY_GRACE_S:-600}
start=$(date +%s)
last_probe=0

relay_alive() {
  # Baseline listeners on this image are 48271 (relay control) and 2024;
  # the tunnel's data ports show up beyond those when the relay is up.
  # Any OTHER local service (dev server, jupyter) would also match and
  # make this loop spend a real probe client per PROBE_EVERY_S against a
  # dead tunnel, so both sides are configurable: set GMM_HW_RELAY_PORTS
  # to the relay's known data ports (e.g. '8471|8472') to match them
  # explicitly, or extend GMM_HW_IGNORE_PORTS with the extra local
  # listeners to ignore.
  # Comma OR pipe separators, like RELAY_PORTS below: the raw value was
  # interpolated verbatim before, so a comma-separated list ('8888,9999')
  # became a single impossible port pattern and ignored nothing.
  local ignore="48271|2024${GMM_HW_IGNORE_PORTS:+|${GMM_HW_IGNORE_PORTS//,/|}}"
  local ports
  ports=$(ss -tln 2>/dev/null | awk '{print $4}' | grep -oE '[0-9]+$' \
    | grep -vE "^(${ignore})$" | grep .)
  if [ -n "${GMM_HW_RELAY_PORTS:-}" ]; then
    # Accept comma or pipe separators. printf (not echo): with no ports
    # left, echo would still emit one empty line, which a stray trailing
    # separator in the pattern ('8471|' -> '^(8471|)$') matches -- a dead
    # relay reported alive. printf '%s' of an empty string feeds grep
    # nothing, so the check stays dead. Pinned by tests/test_hw_waiter.py.
    printf '%s' "$ports" | grep -qE "^(${GMM_HW_RELAY_PORTS//,/|})$"
  else
    [ -n "$ports" ]
  fi
}

machine_quiet() {
  # NOT pgrep -f: the build-driver's own command line quotes these very
  # words (its system prompt mentions pytest/bench.py), so match against
  # ps args with the driver's wrapper processes filtered out first.
  ! ps -eo args | grep -vE 'claude|grep' \
    | grep -qE 'pytest|bench\.py|bench_kernel_precision|bench_streaming|bench_components'
}

# Sourcing mode for tests: define the functions above, skip the wait loop
# (tests/test_hw_waiter.py stubs `ss`/`ps` on PATH and probes
# relay_alive/machine_quiet directly -- these heuristics have been
# review-flagged repeatedly and must not regress silently). The exit
# fallback keeps an EXECUTED script with the var leaked from a test env a
# no-op too (top-level `return` errors when not sourced).
[ "${GMM_HW_SOURCE_ONLY:-}" = "1" ] && { return 0 2>/dev/null || exit 0; }

while :; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE_S" ]; then
    echo "hw_wait: deadline reached without a live tunnel; giving up"
    exit 1
  fi
  if relay_alive && [ $((now - last_probe)) -ge "$PROBE_EVERY_S" ]; then
    if ! machine_quiet; then
      # Bounded hold only: a busy machine contaminates bench.py's
      # in-process CPU baselines (secondary data), but tunnel windows are
      # rare and short (2026-07-31: the relay flapped up for minutes
      # during a 16-min pytest run and was gone again after) -- the TPU
      # timings themselves are unaffected by host load, so after the
      # grace period proceed anyway and let the vs_baseline denominators
      # carry the risk.
      busy_since=${busy_since:-$now}
      if [ $((now - busy_since)) -lt "$BUSY_GRACE_S" ]; then
        echo "hw_wait: $(date -u +%H:%M:%S) relay up but machine busy; holding ($((now - busy_since))s)"
        sleep "$POLL_S"
        continue
      fi
      echo "hw_wait: $(date -u +%H:%M:%S) machine still busy after grace -- proceeding; CPU baselines in this session may be contaminated"
    fi
    busy_since=""
    echo "hw_wait: relay listener up; probing device ($(date -u +%H:%M:%S))"
    last_probe=$now
    if timeout 300 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "hw_wait: $(date -u +%H:%M:%S) tunnel ALIVE; settling, then running hw_session.sh"
      sleep "${HW_STEP_SETTLE_S:-45}"
      # A pytest/bench run may have started during probe+settle; same
      # bounded hold as above -- the live tunnel outranks clean CPU
      # baselines after the grace period.
      quiet_hold=0
      until machine_quiet; do
        if [ "$quiet_hold" -ge "$BUSY_GRACE_S" ]; then
          echo "hw_wait: $(date -u +%H:%M:%S) still busy after grace -- launching anyway (CPU baselines may be contaminated)"
          break
        fi
        echo "hw_wait: $(date -u +%H:%M:%S) tunnel alive but machine busy; holding (${quiet_hold}s)"
        sleep "$POLL_S"
        quiet_hold=$((quiet_hold + POLL_S))
      done
      # Child, not exec: if the tunnel wedges mid-session the session
      # aborts with rc 3 (its anti-pile-up contract) and THIS loop must
      # survive to resume it when the tunnel comes back. rc 0 = every
      # step DONE; anything else is left for the next attempt too.
      bash examples/hw_session.sh
      rc=$?
      if [ "$rc" -eq 0 ]; then
        # hw_session.sh wrote $LOGDIR/ANALYSIS.md itself (it owns LOGDIR).
        echo "hw_wait: session complete"
        exit 0
      fi
      if [ "$rc" -eq 4 ]; then
        # Measurements all captured; only the offline analyzer broke.
        # Retrying would re-fail deterministically and burn a probe client
        # per attempt against the live tunnel -- stop loudly instead.
        echo "hw_wait: session data captured but ANALYSIS FAILED (rc=4);"
        echo "         fix examples/analyze_hw_session.py and re-run it by hand"
        exit 4
      fi
      echo "hw_wait: session aborted (rc=$rc); back to waiting"
      last_probe=$(date +%s)   # the session just proved the tunnel is sick
      sleep "$POLL_S"
      continue
    fi
    echo "hw_wait: probe hung/failed; backing off ${PROBE_EVERY_S}s"
  else
    # Not probing this tick (relay down or probe not due): any busy-hold
    # accounting belongs to a dead relay window; reset it so the next
    # window starts its grace period fresh.
    busy_since=""
  fi
  sleep "$POLL_S"
done
