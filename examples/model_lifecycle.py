"""Model lifecycle: fit -> save -> reload -> predict -> warm-start refine.

Demonstrates the round-trip surfaces added over the reference (whose
``.summary`` files were write-only): the same file a reference user already
has on disk loads here, scores new data, and seeds further fitting.

  PYTHONPATH=. python examples/model_lifecycle.py [--device=cpu]
"""

import sys
import tempfile

import numpy as np

from cuda_gmm_mpi_tpu import GaussianMixture
from cuda_gmm_mpi_tpu.io.writers import write_summary


def main() -> int:
    device = None
    for a in sys.argv[1:]:
        if a.startswith("--device="):
            device = a.split("=", 1)[1]
    kw = dict(min_iters=20, max_iters=20, chunk_size=8192)
    if device:
        kw["device"] = device

    rng = np.random.default_rng(1)
    k, d = 4, 6
    centers = rng.normal(scale=10.0, size=(k, d))
    data = (centers[rng.integers(0, k, 20_000)]
            + rng.normal(size=(20_000, d))).astype(np.float32)

    # 1. Fit (fixed K here; see fit_synthetic.py for the order search).
    gm = GaussianMixture(k, target_components=k, **kw).fit(data)
    print(f"fit: loglik={gm.loglik_:.1f}  n_iter={gm.n_iter_}")

    with tempfile.TemporaryDirectory() as tmp:
        path = f"{tmp}/model.summary"
        # 2. Save in the reference's own .summary format.
        write_summary(path, gm.result_)

        # 3. Reload -- works for reference-produced files too.
        gm2 = GaussianMixture.from_summary(path, **kw)
        new = (centers[rng.integers(0, k, 1_000)]
               + rng.normal(size=(1_000, d))).astype(np.float32)
        agree = float(np.mean(gm2.predict(new) == gm.predict(new)))
        print(f"reload: predict agreement on fresh data = {agree:.3f}")

        # 4. Warm-start: refine the saved model with more EM on new data.
        gm3 = GaussianMixture(k, target_components=k, means_init=gm2.means_,
                              **kw).fit(np.concatenate([data, new]))
        print(f"refine: loglik={gm3.loglik_:.1f} "
              f"(max mean shift {np.abs(gm3.means_ - gm2.means_).max():.3f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
