"""Runnable distributed-fit example: one SPMD program over a device mesh.

Fits the same mixture three ways and checks they agree:
  1. single device (no mesh),
  2. 8-way event sharding -- mesh (8, 1), the reference's pure
     data-parallel layout (every GPU holds an event shard,
     gaussian.cu:289-301), one fused psum of the sufficient-statistics
     pytree per EM iteration,
  3. 4-way events x 2-way clusters -- mesh (4, 2), the cross-device
     generalization of the reference's per-cluster grid parallelism
     (estep1's grid.y, gaussian_kernel.cu:383): the E-step normalization
     runs a two-stage collective log-sum-exp over the cluster axis.

No TPU pod needed: with no real multi-device platform, this forces 8
virtual CPU devices (the same harness tests/conftest.py uses), which
exercises the REAL shard_map/psum code paths -- on hardware the identical
config just picks up the real chips. See docs/DISTRIBUTED.md for the
multi-host (MPI-cluster equivalent) variant of the same program.

Run:  PYTHONPATH=. python examples/fit_sharded.py
"""

import numpy as np


def main():
    import os

    import jax

    if os.environ.get("GMM_EXAMPLE_PLATFORM", "cpu") == "cpu":
        # 8 virtual CPU devices, pinned BEFORE any device use (probing
        # jax.devices() first would initialize -- or hang on -- whatever
        # accelerator plugin the image preloads; see tests/conftest.py).
        # On a real >=8-device platform run with GMM_EXAMPLE_PLATFORM=native.
        from cuda_gmm_mpi_tpu.utils.compat import force_cpu_devices

        force_cpu_devices(8)

    from cuda_gmm_mpi_tpu import GMMConfig, fit_gmm

    rng = np.random.default_rng(0)
    k_true, d, n = 6, 8, 64_000
    centers = rng.normal(scale=6.0, size=(k_true, d))
    data = (centers[rng.integers(0, k_true, n)]
            + rng.normal(size=(n, d))).astype(np.float32)

    base = dict(min_iters=10, max_iters=40, chunk_size=4096)
    r_single = fit_gmm(data, 12, 0, config=GMMConfig(**base))
    r_data = fit_gmm(data, 12, 0, config=GMMConfig(mesh_shape=(8, 1), **base))
    r_2d = fit_gmm(data, 12, 0, config=GMMConfig(mesh_shape=(4, 2), **base))

    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform})")
    for name, r in (("single", r_single), ("mesh (8,1)", r_data),
                    ("mesh (4,2)", r_2d)):
        print(f"{name:11s} ideal K={r.ideal_num_clusters:2d}  "
              f"rissanen={r.min_rissanen:.1f}  loglik={r.final_loglik:.1f}")

    # Sharded == single (float32 reduction-order tolerance): the sharding
    # changes WHERE the math runs, not the answer.
    assert r_data.ideal_num_clusters == r_single.ideal_num_clusters
    assert r_2d.ideal_num_clusters == r_single.ideal_num_clusters
    np.testing.assert_allclose(r_data.min_rissanen, r_single.min_rissanen,
                               rtol=1e-4)
    np.testing.assert_allclose(r_2d.min_rissanen, r_single.min_rissanen,
                               rtol=1e-4)
    print("parity OK: both meshes reproduce the single-device sweep")


if __name__ == "__main__":
    main()
