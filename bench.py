"""Benchmark: EM iterations/sec on the north-star config (1M x 24, K=100).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = full EM iterations per second (fused E-step + M-step + constants,
               the reference's per-iteration loop body, gaussian.cu:532-755) on
               the default JAX platform (TPU when available).
vs_baseline  = speedup over an optimized vectorized CPU (NumPy/BLAS)
               implementation of the identical iteration, measured on a
               subsample and scaled per-event -- the same headline comparison
               the reference makes (README.txt:20: "~100x vs optimized CPU").

Smaller shapes are used automatically on CPU-only hosts so the bench stays
fast; the reported metric is always normalized to iterations/sec at the
measured shape, with the shape recorded in the JSON.

Sweep mode (``--sweep`` or GMM_BENCH_SWEEP=1): instead of fixed-K
iters/sec, time the HEADLINE workload -- a full K0 -> 1 Rissanen
order search -- twice on the same data and seed: cluster-width bucketing
on (``sweep_k_buckets='pow2'``) vs off. The JSON carries both walls,
per-K seconds, the compiled EM widths, and the parity check (selected K
equal, max relative loglik diff); ``vs_baseline`` is the off/bucketed
wall ratio (the bucketing speedup), NOT the NumPy baseline. Size knobs:
GMM_BENCH_SWEEP_K (default 64), GMM_BENCH_SWEEP_N (default 1M accel /
20k CPU), GMM_BENCH_SWEEP_D (24 accel / 16 CPU).

Restart mode (``--restarts`` or GMM_BENCH_RESTARTS=R): batched-vs-
sequential n_init A/B -- the same K0 -> 1 order search fitted with R
restarts vmapped into single-dispatch batched EM
(``restart_batch_size=R``) vs run as R sequential fits
(``restart_batch_size=1``), same data and seeds. The JSON carries both
walls plus winner parity (same init index / selected K / relative score
diff); ``vs_baseline`` is the sequential/batched wall ratio. Size knobs:
GMM_BENCH_RESTART_{N,D,K,ITERS} (see run_restart_bench).

Envelope mode (``--envelope`` or GMM_BENCH_ENVELOPE=1): fused-Pallas-vs-
jnp A/B of fixed-iteration EM on the reference's first-class envelope
(K=512, D=32 -- gaussian.h:10,16), full + diag covariance, both walls +
parity in ONE record; ``vs_baseline`` is the jnp/fused wall ratio on
full covariance. CPU fallback runs the kernel in interpret mode
(correctness, not speed) and is tagged ``accelerator_unavailable``.
Size knobs: GMM_BENCH_ENVELOPE_{N,D,K,ITERS,BLOCK} (run_envelope_bench).

Serve mode (``--serve`` or GMM_BENCH_SERVE=1): cold-vs-warm A/B of the
serving subsystem -- fit a small mixture, export it to a temp registry,
drive the in-process micro-batched server: the cold first request
(registry load + AOT compile) vs the steady state (>= 100 varying-N
requests after one warm-up per N-bucket), with the zero-recompile proof
bit in the record; ``vs_baseline`` is cold / warm-p50. The record also
carries the server's resilience counters (shed / deadline_expired /
breaker trips / reloads -- stream rev v1.7) so soak runs surface
degradation, all-zero on a clean A/B. Size knobs:
GMM_BENCH_SERVE_{N,D,K,REQUESTS} (run_serve_bench).

HTTP mode (``--http`` or GMM_BENCH_HTTP=1): rev v2.7 network-tier
contract -- fit + export a model, launch a REAL ``gmm serve --http 0
--workers W`` subprocess tree, and drive it closed-loop with C
concurrent :class:`GMMClient` threads; mid-load, SIGKILL one worker
process and keep the load running. ONE record carries the warm QPS and
p50/p99 over TCP, the ``zero_failed_requests`` proof bit (the pool's
sibling retry + respawn must hide the kill from every client), the
kill->respawned recovery wall, client retry/shed counters, and the
server's ``serve_summary.http`` rollup; ``vs_baseline`` is http-p50 /
in-process-p50 from a same-shape in-process server (what the network +
pool tier costs per request). Size knobs:
GMM_BENCH_HTTP_{N,D,K,WORKERS,CLIENTS,REQUESTS} (run_http_bench).

Drift mode (``--drift`` or GMM_BENCH_DRIFT=1): rev v2.4 drift-plane
contract -- fit + export a model (training envelope in the registry),
serve it with the drift plane on, replay in-distribution traffic then
deliberately shifted traffic, and flush one drift window per phase;
ONE record carries psi_in (must sit under the alarm threshold),
psi_shifted (must sit over it), the drift_alarm-fired bit, and the
drift-on/drift-off serve wall ratio on identical warmed traffic
(``vs_baseline`` = that overhead ratio; the plane reuses the request's
own 'proba' block, so ~1.0 is the expectation). Size knobs:
GMM_BENCH_DRIFT_{N,D,K,REQUESTS} (run_drift_bench).

Lifecycle mode (``--lifecycle`` or GMM_BENCH_LIFECYCLE=1): rev v2.6
closed-loop contract -- fit + export a model, serve it with the drift
plane AND a LifecycleController bound, then drive the whole loop in
ONE record: injected drift traffic (alarm) -> shadow minibatch-EM
retrain -> canary gates + duplicate-dispatch shadow window -> atomic
promotion -> injected post-promotion score regression -> automatic
rollback, with per-phase walls, the canary gate values (PSI/KS/mean
regression vs tolerance), and the ``rollback_restored_bit_identical``
proof bit (restored npz leaves AND a fixed probe's scores match the
pre-promotion server exactly). ``vs_baseline`` is the lifecycle-on /
lifecycle-off steady-serve wall ratio on identical warmed traffic
(the controller rides the tick loop, so ~1.0 is the expectation).
Size knobs: GMM_BENCH_LIFECYCLE_{N,D,K,REQUESTS}
(run_lifecycle_bench).

Tenancy mode (``--tenancy`` or GMM_BENCH_TENANCY=1): batched-fleet-vs-
sequential multi-tenant A/B -- T independent per-tenant datasets fitted
once through ``fit_fleet`` (packed groups, one fleet EM dispatch per
sweep step; tenancy/) and once as T sequential solo fits, with BOTH
walls and per-tenant winner/loglik parity bits in ONE record;
``vs_baseline`` is sequential / fleet. Size knobs: GMM_BENCH_TENANTS +
GMM_BENCH_TENANCY_{N,D,K,ITERS} (run_tenancy_bench).

Obs mode (``--obs`` or GMM_BENCH_OBS=1): telemetry-overhead A/B/C --
one fit measured with telemetry off, with the --metrics-file stream,
and with the full --metrics-port live plane (OpenMetrics exporter +
resource sampler + trace spans) while a client thread scrapes /metrics
throughout; ONE record carries all three walls, both overhead ratios,
and the scrape/span/sampler health bits proving the plane actually ran.
Size knobs: GMM_BENCH_OBS_{N,D,K,ITERS} + GMM_BENCH_OBS_BOUND
(run_obs_bench).

Profile mode (``--profile`` or GMM_BENCH_PROFILE=1): rev v2.2 compile-
introspection contract -- the same fit twice with the CompileWatch
active, asserting the run_summary.profile block's shape (site compiles
vs XLA compiles, per-site sums) and that the two identical runs
``gmm diff`` clean (diff_exit 0 rides in the record; vs_baseline 1.0 =
clean). Size knobs: GMM_BENCH_PROFILE_{N,D,K,ITERS} (run_profile_bench).

Timeline mode (``--timeline`` or GMM_BENCH_TIMELINE=1): rev v2.3 trace
export contract -- one fit with the live plane on (spans + clock-bearing
heartbeats), its stream exported through ``telemetry.timeline`` into a
Chrome/Perfetto trace, the emitted document re-checked by the
``--validate`` structural oracle; ONE record carries the event / slice /
counter / track counts, the stream's alignment mode (must be "clock"),
and the validate-pass bit (vs_baseline 1.0 = clean). Size knobs:
GMM_BENCH_TIMELINE_{N,D,K,ITERS} (run_timeline_bench).

Ingest mode (``--ingest`` or GMM_BENCH_INGEST=1): host-resident vs
pipelined out-of-core ingestion A/B on one BIN dataset -- each mode
(resident / pipelined / pipelined+minibatch) fits in its own subprocess
so ru_maxrss isolates per-mode peak host memory; ONE record carries all
walls, per-mode RSS growth over the post-device-init baseline, the
resident==pipelined bit-identical-loglik parity bit, and the minibatch
relative error; ``vs_baseline`` is the RSS-growth ratio resident /
pipelined. Size knobs: GMM_BENCH_INGEST_{N,D,K,BLOCK,ITERS}
(run_ingest_bench).

Env knobs: GMM_BENCH_CPU=1 (deliberate CPU run, rc 0); GMM_BENCH_PRECISION
(matmul precision override); GMM_BENCH_PRECOMPUTE=1/0 (feature-hoist A/B,
full-covariance in-memory configs; defaults ON for CPU runs -- the NumPy
baseline hoists its own features -- and OFF on the accelerator pending the
hw-session routing decision); GMM_BENCH_CHUNK (chunk size on EITHER
platform; accelerator default 131072, CPU default 4096 from the round-5
cache sweep); GMM_BENCH_MAX_N (CPU-run event cap, default 100000 -- smoke
runs shrink it); GMM_BENCH_WATCHDOG_S (mid-run dead-device deadline,
default 1800);
GMM_BENCH_METRICS (opt-in: a JSONL path -- sweep configs run the timed fit
with the telemetry recorder attached and the per-K iteration/seconds
numbers are read back from the schema-versioned stream instead of the
in-process sweep_log, exercising the same consumer path `gmm report`
uses; the artifact notes telemetry_source=jsonl);
GMM_BENCH_PROBE_RETRIES / GMM_BENCH_PROBE_WAIT (accelerator probe budget:
default is ONE probe attempt -- fail over to CPU after one hang -- with
retries opt-in; legacy GMM_BENCH_PROBE_{ATTEMPTS,WAIT_S} and
GMM_BENCH_PROBE_TIMEOUT_S still honored);
GMM_BENCH_SETTLE_S (pause between the probe client's disconnect and this
process's device init, default 10); GMM_BENCH_REQUIRE_ACCEL=1 (on probe
failure, emit the unavailable artifact and exit 3 immediately instead of
measuring the CPU fallback -- for unattended accelerator sessions where a
multi-hour CPU run of a 10M-event config would be pure waste).
Exit codes: 0 = measured on the intended platform; 2 = bad usage; 3 = no
accelerator (probe fallback or watchdog; JSON carries
accelerator_unavailable=true).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# North-star cross-session tunnel band (ms/iter) from docs/PERF.md's
# "session-variance band" — update BOTH together when a hardware session
# widens it. Emitted in the north-star accelerator JSON so a driver
# diffing BENCH_r{N} across rounds can tell tunnel weather from a code
# regression.
SESSION_BAND_MS_PER_ITER = [8.6, 12.8]


def probe_default_platform(timeout_s: float = 180.0, attempts: int = 1,
                           retry_wait_s: float = 90.0, *,
                           honor_env: bool = True) -> bool:
    """True if the default JAX platform initializes in a fresh subprocess.

    Device init happens in-process and cannot be interrupted once started
    (a wedged TPU tunnel would hang the bench forever), so probe from a
    disposable child first. Default: ONE attempt -- a hung probe fails
    over immediately. The old 5 x 180s + 4 x 90s retry ladder burned
    ~7.5 minutes of every unattended session against tunnels that never
    came back (BENCH_r05's tail); a wedge that DOES clear is the rarer
    case, so retrying is now opt-in: GMM_BENCH_PROBE_RETRIES=N adds N
    retries with GMM_BENCH_PROBE_WAIT seconds between (legacy aliases
    GMM_BENCH_PROBE_ATTEMPTS -- an absolute count that wins when set --
    and GMM_BENCH_PROBE_WAIT_S still work); GMM_BENCH_PROBE_TIMEOUT_S
    bounds each probe. ``honor_env=False`` makes the explicit arguments
    binding (callers like __graft_entry__.entry() that deliberately want
    one quick attempt, regardless of a bench-oriented environment).
    """
    if honor_env:
        timeout_s = float(
            os.environ.get("GMM_BENCH_PROBE_TIMEOUT_S", timeout_s))
        if os.environ.get("GMM_BENCH_PROBE_ATTEMPTS") not in (None, ""):
            attempts = int(os.environ["GMM_BENCH_PROBE_ATTEMPTS"])
        elif os.environ.get("GMM_BENCH_PROBE_RETRIES") not in (None, ""):
            attempts = int(os.environ["GMM_BENCH_PROBE_RETRIES"]) + 1
        retry_wait_s = float(
            os.environ.get("GMM_BENCH_PROBE_WAIT")
            or os.environ.get("GMM_BENCH_PROBE_WAIT_S")
            or retry_wait_s)
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True,
            )
            if r.returncode == 0:
                return True
            # Fast nonzero exit = deterministic breakage (driver mismatch,
            # missing plugin): retrying cannot help, fall back now.
            return False
        except subprocess.TimeoutExpired:
            pass  # hang = the clearable wedge; worth retrying
        if i + 1 < attempts:
            print(f"bench.py: accelerator probe {i + 1}/{attempts} hung; "
                  f"retrying in {retry_wait_s:.0f}s", file=sys.stderr)
            time.sleep(retry_wait_s)
    return False


def settle_after_probe(*, honor_env: bool = True) -> None:
    """Pause between a probe client's disconnect and in-process device init.

    The probe subprocess was itself a tunnel client; give the
    single-admission relay a moment to release it before the caller's own
    (uninterruptible) device init connects. Back-to-back admission is a
    suspected wedge trigger (2026-07-31 session: one client hung in init
    ~6s after the previous client exited). GMM_BENCH_SETTLE_S overrides
    the default 10s; empty-string-safe, negative values clamp to 0.
    ``honor_env=False`` keeps the default settle even when bench-oriented
    env is set (mirrors probe_default_platform: __graft_entry__.entry()
    must not lose its anti-wedge settle to a stray GMM_BENCH_SETTLE_S=0).
    """
    settle_s = 10.0
    if honor_env:
        settle_s = float(os.environ.get("GMM_BENCH_SETTLE_S") or settle_s)
    time.sleep(max(0.0, settle_s))


def baseline_params(state, k, dtype=np.float32):
    """Extract the NumPy-baseline parameter dict from a GMMState.

    Single source for what the CPU baseline iterates on (the parity test
    tests/test_bench_contract.py certifies numpy_em_iteration* against the
    framework through this same extraction, so the two cannot diverge
    silently). The pi clamp mirrors the framework's 1e-10 floor.
    """
    return {
        "means": np.asarray(state.means, dtype)[:k],
        "Rinv": np.asarray(state.Rinv, dtype)[:k],
        "constant": np.asarray(state.constant, dtype)[:k],
        "pi": np.maximum(np.asarray(state.pi, dtype)[:k], 1e-10),
        "avgvar": np.asarray(state.avgvar, dtype)[:k],
    }


def numpy_em_iteration(x, x2, params):
    """One fused EM iteration in NumPy (same matmul formulation, BLAS-backed)."""
    mu, Rinv, const, pi, avgvar = (
        params["means"], params["Rinv"], params["constant"], params["pi"],
        params["avgvar"],
    )
    K, D = mu.shape
    A = Rinv.reshape(K, D * D)
    b = np.einsum("kde,ke->kd", Rinv, mu)
    c = np.einsum("kd,kd->k", b, mu)
    q = x2 @ A.T - 2.0 * (x @ b.T) + c[None, :]
    logp = -0.5 * q + const[None, :] + np.log(pi)[None, :]
    m = logp.max(axis=1, keepdims=True)
    e = np.exp(logp - m)
    denom = e.sum(axis=1, keepdims=True)
    ll = float((m + np.log(denom)).sum())
    w = e / denom
    Nk = w.sum(axis=0)
    M1 = w.T @ x
    M2 = (w.T @ x2).reshape(K, D, D)
    mu_new = M1 / np.maximum(Nk, 1e-30)[:, None]
    R = M2 - Nk[:, None, None] * (mu_new[:, :, None] * mu_new[:, None, :])
    R += avgvar[:, None, None] * np.eye(D, dtype=x.dtype)[None]
    R /= np.maximum(Nk, 1e-30)[:, None, None]
    Rinv_new = np.linalg.inv(R)
    sign, logdet = np.linalg.slogdet(R)
    const_new = -D * 0.5 * np.log(2 * np.pi) - 0.5 * logdet
    pi_new = Nk / Nk.sum()
    return dict(means=mu_new.astype(x.dtype), Rinv=Rinv_new.astype(x.dtype),
                constant=const_new.astype(x.dtype), pi=pi_new.astype(x.dtype),
                avgvar=avgvar), ll


def numpy_em_iteration_diag(x, x2, params):
    """One fused diagonal-covariance EM iteration in NumPy (x2 = x*x, [N, D]).

    The like-for-like CPU baseline for diag configs: same DIAG_ONLY math the
    accelerator runs (apply_mstep diag branch), so vs_baseline compares
    identical iterations rather than charging the CPU for full-covariance
    work the accelerator never did.
    """
    mu, Rinv, const, pi, avgvar = (
        params["means"], params["Rinv"], params["constant"], params["pi"],
        params["avgvar"],
    )
    K, D = mu.shape
    a = np.diagonal(Rinv, axis1=-2, axis2=-1)  # [K, D]
    q = x2 @ a.T - 2.0 * (x @ (a * mu).T) + np.sum(a * mu * mu, axis=1)[None, :]
    logp = -0.5 * q + const[None, :] + np.log(pi)[None, :]
    m = logp.max(axis=1, keepdims=True)
    e = np.exp(logp - m)
    denom = e.sum(axis=1, keepdims=True)
    ll = float((m + np.log(denom)).sum())
    w = e / denom
    Nk = w.sum(axis=0)
    M1 = w.T @ x
    M2 = w.T @ x2                                        # [K, D] diagonal sums
    mu_new = M1 / np.maximum(Nk, 1e-30)[:, None]
    var = (M2 - Nk[:, None] * mu_new * mu_new + avgvar[:, None])
    var /= np.maximum(Nk, 1e-30)[:, None]
    R = np.zeros((K, D, D), x.dtype)
    Rinv_new = np.zeros((K, D, D), x.dtype)
    idx = np.arange(D)
    R[:, idx, idx] = var
    Rinv_new[:, idx, idx] = 1.0 / var
    const_new = -D * 0.5 * np.log(2 * np.pi) - 0.5 * np.log(var).sum(axis=1)
    pi_new = Nk / Nk.sum()
    return dict(means=mu_new.astype(x.dtype), Rinv=Rinv_new,
                constant=const_new.astype(x.dtype), pi=pi_new.astype(x.dtype),
                avgvar=avgvar), ll


def run_sweep_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --sweep mode: bucketed-vs-off A/B of a full K0 -> 1 order search.

    Both runs fit the SAME data with the SAME seed through the host-driven
    sweep; only ``sweep_k_buckets`` differs. Executables are warmed with a
    1-iteration-per-K pass first (min/max_iters are dynamic args, so the
    warm sweep compiles exactly the executables the timed sweep reuses),
    keeping compile time out of the timed walls on both sides.
    """
    on_accel = platform not in ("cpu",)
    k0 = int(os.environ.get("GMM_BENCH_SWEEP_K") or 64)
    n = int(os.environ.get("GMM_BENCH_SWEEP_N")
            or (1_000_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_SWEEP_D") or (24 if on_accel else 16))
    iters = 5 if on_accel else 3
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k0, d))
    data = (
        centers[rng.integers(0, k0, n)]
        + rng.normal(scale=1.0, size=(n, d))
    ).astype(np.float32)

    def one(mode: str):
        cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                        sweep_k_buckets=mode)
        model = GMMModel(cfg)
        # Warm sweep at 1 iter/K: visits the same widths (same merge
        # inputs after 1 iteration may diverge from the timed trajectory,
        # so a width can stay cold in pathological cases; the timed wall
        # then includes that compile -- conservative for the bucketed side,
        # which has more widths to warm).
        warm = GMMConfig(min_iters=1, max_iters=1, chunk_size=chunk,
                         sweep_k_buckets=mode)
        fit_gmm(data, k0, 0, warm, model=model)
        t0 = time.perf_counter()
        res = fit_gmm(data, k0, 0, cfg, model=model)
        wall = time.perf_counter() - t0
        log = res.sweep_log
        return {
            "wall_s": round(wall, 3),
            "ideal_k": int(res.ideal_num_clusters),
            "final_loglik": float(res.final_loglik),
            "total_iters": int(sum(r[3] for r in log)),
            "ks": [int(r[0]) for r in log],
            "logliks": [float(r[1]) for r in log],
            "per_k_seconds": [round(float(r[4]), 5) for r in log],
        }, res

    bucketed, res_b = one("pow2")
    off, res_o = one("off")

    # Parity of the A/B (same data, same seed): selected K and per-K
    # loglik trajectories must agree -- the speedup is only meaningful if
    # the answers match.
    n_common = min(len(bucketed["logliks"]), len(off["logliks"]))
    rel = [
        abs(a - b) / max(abs(b), 1e-30)
        for a, b in zip(bucketed["logliks"][:n_common],
                        off["logliks"][:n_common])
    ]
    speedup = off["wall_s"] / max(bucketed["wall_s"], 1e-9)
    result = {
        "metric": f"order-search sweep wall ({n}x{d}, K={k0}->1, "
                  f"{platform})",
        "value": bucketed["wall_s"],
        "unit": "s",
        # A/B ratio (off / bucketed), NOT the NumPy-vs-accelerator
        # baseline the fixed-K metric reports.
        "vs_baseline": round(speedup, 3),
        "accelerator_unavailable": accel_unavailable,
        "sweep": {
            "k0": k0, "n": n, "d": d, "em_iters_per_k": iters,
            "chunk_size": chunk,
            "bucketed": bucketed,
            "off": off,
            "speedup": round(speedup, 3),
            "ideal_k_equal": bucketed["ideal_k"] == off["ideal_k"],
            "ks_equal": bucketed["ks"] == off["ks"],
            "max_rel_loglik_diff": max(rel) if rel else None,
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_restart_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --restarts mode: batched-vs-sequential n_init A/B.

    Fits the SAME data with the SAME seeds through the full K0 -> 1 order
    search twice: once with the restarts batched into single-dispatch
    vmapped EM (``restart_batch_size=R``), once sequentially
    (``restart_batch_size=1`` -- the degenerate case). Both sides are
    warmed with a 1-iteration-per-K pass on their own model so compile
    time stays out of the timed walls (min/max_iters are dynamic args).
    ``vs_baseline`` is the sequential/batched wall ratio (the batching
    speedup), and the record carries winner parity (same init index, same
    selected K, relative score diff) -- the speedup is only meaningful if
    both drivers pick the identical winner.

    Size knobs: GMM_BENCH_RESTARTS (R, default 4), GMM_BENCH_RESTART_N
    (default 200k accel / 20k CPU), GMM_BENCH_RESTART_D (16 / 8),
    GMM_BENCH_RESTART_K (32 / 16), GMM_BENCH_RESTART_ITERS (5 / 4).
    """
    on_accel = platform not in ("cpu",)
    r_init = int(os.environ.get("GMM_BENCH_RESTARTS") or 4)
    n = int(os.environ.get("GMM_BENCH_RESTART_N")
            or (200_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_RESTART_D") or (16 if on_accel else 8))
    k0 = int(os.environ.get("GMM_BENCH_RESTART_K")
             or (32 if on_accel else 16))
    iters = int(os.environ.get("GMM_BENCH_RESTART_ITERS")
                or (5 if on_accel else 4))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k0, d))
    data = (
        centers[rng.integers(0, k0, n)]
        + rng.normal(scale=1.0, size=(n, d))
    ).astype(np.float32)

    def one(batch: int):
        cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                        n_init=r_init, seed=0, restart_batch_size=batch)
        model = GMMModel(cfg)
        warm = GMMConfig(min_iters=1, max_iters=1, chunk_size=chunk,
                         n_init=r_init, seed=0, restart_batch_size=batch)
        fit_gmm(data, k0, 0, warm, model=model)
        t0 = time.perf_counter()
        res = fit_gmm(data, k0, 0, cfg, model=model)
        wall = time.perf_counter() - t0
        return {
            "wall_s": round(wall, 3),
            "winner_init": (int(res.init_index)
                            if res.init_index is not None else None),
            "ideal_k": int(res.ideal_num_clusters),
            "score": float(res.min_rissanen),
            "final_loglik": float(res.final_loglik),
        }

    batched = one(r_init)
    sequential = one(1)
    speedup = sequential["wall_s"] / max(batched["wall_s"], 1e-9)
    rel_score = (abs(batched["score"] - sequential["score"])
                 / max(abs(sequential["score"]), 1e-30))
    result = {
        "metric": f"n_init={r_init} restart wall ({n}x{d}, K={k0}->1, "
                  f"{platform})",
        "value": batched["wall_s"],
        "unit": "s",
        # A/B ratio (sequential / batched), NOT the NumPy baseline.
        "vs_baseline": round(speedup, 3),
        "accelerator_unavailable": accel_unavailable,
        "restarts": {
            "n_init": r_init, "n": n, "d": d, "k0": k0,
            "em_iters_per_k": iters, "chunk_size": chunk,
            "batched": batched,
            "sequential": sequential,
            "speedup": round(speedup, 3),
            "winner_equal": (batched["winner_init"]
                             == sequential["winner_init"]),
            "ideal_k_equal": batched["ideal_k"] == sequential["ideal_k"],
            "rel_score_diff": rel_score,
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_envelope_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --envelope mode: fused-Pallas-vs-jnp A/B on the reference
    envelope (MAX_CLUSTERS=512, NUM_DIMENSIONS=32 -- gaussian.h:10,16).

    Times fixed-iteration EM twice on the SAME data and seed state: once
    with ``estep_backend='pallas'`` (the batched-capable fused kernel +
    fused M-step epilogue -- one kernel round-trip per iteration) and
    once with ``estep_backend='jnp'`` (the XLA path), for BOTH covariance
    families (full + diag). One JSON record carries both walls AND the
    parity check per family -- the speedup is only meaningful if the two
    backends compute the same model. ``vs_baseline`` is the jnp/fused
    wall ratio on the full-covariance family (the kernel speedup), NOT
    the NumPy baseline.

    On CPU the kernel executes in Pallas interpret mode (the record's
    ``backend`` field says so: 'pallas-interpret'), which measures
    correctness, not speed -- a CPU-fallback record is tagged
    ``accelerator_unavailable`` and must never be read as the envelope
    number. Size knobs: GMM_BENCH_ENVELOPE_{N,D,K,ITERS,BLOCK} (defaults
    1M x 32, K=512, 10 iters on an accelerator; tiny interpret-friendly
    shapes on CPU).
    """
    on_accel = platform not in ("cpu",)
    k = int(os.environ.get("GMM_BENCH_ENVELOPE_K")
            or (512 if on_accel else 16))
    n = int(os.environ.get("GMM_BENCH_ENVELOPE_N")
            or (1_000_000 if on_accel else 4_096))
    d = int(os.environ.get("GMM_BENCH_ENVELOPE_D")
            or (32 if on_accel else 8))
    iters = int(os.environ.get("GMM_BENCH_ENVELOPE_ITERS")
                or (10 if on_accel else 2))
    block = int(os.environ.get("GMM_BENCH_ENVELOPE_BLOCK")
                or (512 if on_accel else 256))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)
    precision = os.environ.get("GMM_BENCH_PRECISION") or "highest"

    import jax
    import jax.numpy as jnp

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (
        centers[rng.integers(0, k, n)]
        + rng.normal(scale=1.0, size=(n, d))
    ).astype(np.float32)
    state = seed_clusters_host(data, k)
    eps = convergence_epsilon(n, d)

    def one(backend: str, diag: bool):
        cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                        diag_only=diag, matmul_precision=precision,
                        estep_backend=backend, pallas_block_b=block)
        model = GMMModel(cfg)
        chunks, wts = chunk_events(data, cfg.chunk_size)
        chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
        # Warm the exact executable the timed reps reuse (min/max_iters
        # are dynamic args -- same contract as the fixed-K bench).
        s, _, _ = model.run_em(state, chunks, wts, eps,
                               min_iters=1, max_iters=1)
        jax.block_until_ready(s)
        times = []
        for r in range(3):
            sr = state.replace(means=state.means * (1.0 + 1e-6 * (r + 1)))
            t0 = time.perf_counter()
            s, ll_dev, _ = model.run_em(sr, chunks, wts, eps)
            ll = float(ll_dev)
            times.append(time.perf_counter() - t0)
        dt = min(times)
        return {
            "wall_s": round(dt, 4),
            "iters_per_sec": round(iters / dt, 3),
            "rep_wall_s": [round(t, 4) for t in times],
            "loglik": ll,
            "backend": model.estep_backend,
        }, s

    families = {}
    for name, diag in (("full", False), ("diag", True)):
        fused, s_f = one("pallas", diag)
        ref, s_j = one("jnp", diag)
        mf = np.asarray(jax.device_get(s_f.means))
        mj = np.asarray(jax.device_get(s_j.means))
        rel_ll = (abs(fused["loglik"] - ref["loglik"])
                  / max(abs(ref["loglik"]), 1e-30))
        rel_means = float(np.max(np.abs(mf - mj))
                          / max(float(np.max(np.abs(mj))), 1e-30))
        families[name] = {
            "fused": fused,
            "jnp": ref,
            "speedup": round(ref["wall_s"] / max(fused["wall_s"], 1e-9), 3),
            "rel_loglik_diff": rel_ll,
            "rel_means_diff": rel_means,
            "bit_identical": bool(fused["loglik"] == ref["loglik"]
                                  and np.array_equal(mf, mj)),
            # f32 kernel vs XLA differ in summation association; 1e-4
            # relative separates "same model" from a real divergence.
            "parity_ok": bool(rel_ll < 1e-4 and rel_means < 1e-3),
        }
    speedup = families["full"]["speedup"]
    result = {
        "metric": f"fused EM envelope wall ({n}x{d}, K={k}, {iters} iters, "
                  f"{platform})",
        "value": families["full"]["fused"]["wall_s"],
        "unit": "s",
        # A/B ratio (jnp / fused) on full covariance, NOT the NumPy
        # baseline the fixed-K metric reports.
        "vs_baseline": speedup,
        "accelerator_unavailable": accel_unavailable,
        "envelope": {
            "n": n, "d": d, "k": k, "em_iters": iters,
            "chunk_size": chunk, "block_b": block, "precision": precision,
            "full": families["full"],
            "diag": families["diag"],
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement -- the kernel ran in interpret "
            "mode, so the walls measure correctness, not the envelope")
    return result


def run_tenancy_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --tenancy mode: batched-fleet-vs-sequential multi-tenant A/B.

    Builds T independent per-tenant datasets (varying N within one pow2
    bucket, shared D) and fits them twice with identical seeds/config:
    once through ``fit_fleet`` (tenancy/fleet.py -- packed groups, one
    fleet EM dispatch per sweep step) and once as T sequential
    ``fit_gmm`` calls sharing one model (the solo baseline every
    tenant's parity is defined against). ONE JSON record carries BOTH
    walls plus per-tenant parity bits -- winner K equality and a
    loglik-bit / relative-difference check per tenant -- because the
    speedup is only meaningful if the fleet computed the same models.
    ``vs_baseline`` is sequential/fleet (the packing win).

    Size knobs: GMM_BENCH_TENANTS (T, default 6), GMM_BENCH_TENANCY_N
    (base rows/tenant, default 50k accel / 4k CPU), GMM_BENCH_TENANCY_D
    (8 / 4), GMM_BENCH_TENANCY_K (8 / 4 -- pow2 keeps the bit-parity
    contract), GMM_BENCH_TENANCY_ITERS (5 / 3), GMM_BENCH_TENANCY_MODE
    ('scan' default -- bit-exact; 'vmap' measures the batched-matmul
    throughput shape at tolerance parity).
    """
    on_accel = platform not in ("cpu",)
    t_count = int(os.environ.get("GMM_BENCH_TENANTS") or 6)
    n = int(os.environ.get("GMM_BENCH_TENANCY_N")
            or (50_000 if on_accel else 4_000))
    d = int(os.environ.get("GMM_BENCH_TENANCY_D")
            or (8 if on_accel else 4))
    k = int(os.environ.get("GMM_BENCH_TENANCY_K")
            or (8 if on_accel else 4))
    iters = int(os.environ.get("GMM_BENCH_TENANCY_ITERS")
                or (5 if on_accel else 3))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.tenancy import TenantSpec, fit_fleet

    rng = np.random.default_rng(42)
    tenants = []
    for t in range(t_count):
        # Ragged sizes inside one pow2 bucket: the packing is exercised
        # without multiplying compiled group shapes.
        n_t = n - int(rng.integers(0, max(n // 4, 1)))
        centers = rng.normal(scale=8.0, size=(k, d))
        data = (centers[rng.integers(0, k, n_t)]
                + rng.normal(scale=1.0, size=(n_t, d))
                ).astype(np.float32)
        tenants.append(TenantSpec(f"tenant{t:03d}", data, k))

    fleet_mode = os.environ.get("GMM_BENCH_TENANCY_MODE") or "scan"
    cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                    seed=0, fleet_mode=fleet_mode)

    # Fleet side: one shared model so the warm pass compiles the exact
    # group executables the timed pass reuses (the solo baseline below
    # gets the same treatment).
    fleet_model = GMMModel(cfg)
    fit_fleet(tenants, cfg, model=fleet_model)
    t0 = time.perf_counter()
    fleet = fit_fleet(tenants, cfg, model=fleet_model)
    fleet_wall = time.perf_counter() - t0

    # Sequential baseline: T solo fits sharing ONE model/executables.
    model = GMMModel(cfg)
    for t in tenants:  # warm pass mirrors the fleet's
        fit_gmm(t.data, t.num_clusters, 0, cfg, model=model)
    t0 = time.perf_counter()
    solos = [fit_gmm(t.data, t.num_clusters, 0, cfg, model=model)
             for t in tenants]
    seq_wall = time.perf_counter() - t0

    per_tenant = []
    for spec, solo in zip(tenants, solos):
        tr = fleet[spec.name]
        r = tr.result
        rel_ll = (abs(r.final_loglik - solo.final_loglik)
                  / max(abs(solo.final_loglik), 1e-30))
        per_tenant.append({
            "name": spec.name,
            "n": int(np.asarray(spec.data).shape[0]),
            "ideal_k_equal": bool(
                r.ideal_num_clusters == solo.ideal_num_clusters),
            "loglik_bit_identical": bool(
                r.final_loglik == solo.final_loglik),
            "rel_loglik_diff": rel_ll,
            "parity_ok": bool(
                r.ideal_num_clusters == solo.ideal_num_clusters
                and rel_ll < 1e-6),
        })
    speedup = seq_wall / max(fleet_wall, 1e-9)
    result = {
        "metric": f"fleet fit wall, {t_count} tenants (~{n}x{d}, "
                  f"K={k}->1, {platform})",
        "value": round(fleet_wall, 3),
        "unit": "s",
        # A/B ratio (sequential / fleet), NOT the NumPy baseline.
        "vs_baseline": round(speedup, 3),
        "accelerator_unavailable": accel_unavailable,
        "tenancy": {
            "tenants": t_count, "base_n": n, "d": d, "k": k,
            "em_iters_per_k": iters, "chunk_size": chunk,
            "mode": fleet.mode,
            "groups": len(fleet.groups),
            "fleet_wall_s": round(fleet_wall, 3),
            "sequential_wall_s": round(seq_wall, 3),
            "speedup": round(speedup, 3),
            "dropped": len(fleet.dropped),
            "per_tenant": per_tenant,
            "all_parity_ok": bool(all(t["parity_ok"]
                                      for t in per_tenant)),
            "all_bit_identical": bool(all(t["loglik_bit_identical"]
                                          for t in per_tenant)),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_obs_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --obs mode: telemetry / live-plane overhead A/B/C.

    Fits the SAME data with the same seed and config three times over one
    shared model (shared compiled executables -- the A/B measures
    instrumentation, not compilation):

      off      no telemetry at all (the metrics_file=None fast path:
               one ``active`` attribute check per touchpoint);
      stream   ``--metrics-file`` JSONL stream only (rev <= v2.0 cost);
      live     stream + ``--metrics-port`` live plane (rev v2.1):
               OpenMetrics exporter + resource sampler + trace spans,
               with a client thread scraping ``/metrics`` throughout
               the fit to prove the endpoint serves parseable text
               under load.

    ONE JSON record carries all three walls and both overhead ratios
    (stream/off, live/off). ``within_bound`` checks live/off against the
    documented bound (docs/OBSERVABILITY.md "Overhead": default 1.5x on
    these bench shapes; override GMM_BENCH_OBS_BOUND). Scrape health
    rides along: scrape count, last-scrape parse verdict, and the span /
    sampler-heartbeat record counts from the live stream.

    Size knobs: GMM_BENCH_OBS_N (default 200k accel / 20k CPU),
    GMM_BENCH_OBS_D (16 / 8), GMM_BENCH_OBS_K (16 / 8),
    GMM_BENCH_OBS_ITERS (10 / 6).
    """
    import tempfile
    import threading
    import urllib.request

    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("GMM_BENCH_OBS_N")
            or (200_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_OBS_D") or (16 if on_accel else 8))
    k = int(os.environ.get("GMM_BENCH_OBS_K") or (16 if on_accel else 8))
    iters = int(os.environ.get("GMM_BENCH_OBS_ITERS")
                or (10 if on_accel else 6))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)
    bound = float(os.environ.get("GMM_BENCH_OBS_BOUND") or 1.5)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.telemetry import exporter as tl_exporter
    from cuda_gmm_mpi_tpu.telemetry import read_stream

    rng = np.random.default_rng(7)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)

    tmp = tempfile.mkdtemp(prefix="gmm-obs-")
    base = dict(min_iters=iters, max_iters=iters, chunk_size=chunk,
                seed=0)
    cfg_off = GMMConfig(**base)
    cfg_stream = GMMConfig(metrics_file=os.path.join(tmp, "stream.jsonl"),
                           **base)
    cfg_live = GMMConfig(metrics_file=os.path.join(tmp, "live.jsonl"),
                         metrics_port=0, **base)

    model = GMMModel(cfg_off)
    fit_gmm(data, k, k, cfg_off, model=model)  # warm: compile once
    # Warm the TELEMETRY path too: the first recorder-active fit
    # jit-compiles the streamed-loglik EM variant (a one-time cost of
    # several hundred ms). Unwarmed, the stream pass would absorb it and
    # the "overhead" ratios would measure compilation, not
    # instrumentation.
    fit_gmm(data, k, k,
            GMMConfig(metrics_file=os.path.join(tmp, "warm.jsonl"),
                      metrics_port=0, **base), model=model)

    def timed(cfg):
        t0 = time.perf_counter()
        res = fit_gmm(data, k, k, cfg, model=model)
        return time.perf_counter() - t0, res

    off_wall, off_res = timed(cfg_off)
    stream_wall, stream_res = timed(cfg_stream)

    # Live pass: a background client scrapes /metrics for the fit's
    # whole duration (current_exporter() resolves the ephemeral port the
    # in-fit live plane bound), and the sampler cadence is shrunk so
    # short bench fits still collect samples.
    scrape = {"count": 0, "last": ""}
    stop = threading.Event()

    def _scraper():
        while not stop.is_set():
            ex = tl_exporter.current_exporter()
            port = ex.port if ex is not None else None
            if port:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=2) as resp:
                        scrape["last"] = resp.read().decode("utf-8")
                    scrape["count"] += 1
                except Exception:
                    pass
                stop.wait(0.005)
            else:
                # Warm fits make the live window short; poll tightly so
                # the endpoint's lifetime can't slip between wakeups.
                stop.wait(0.002)

    sampler_env = os.environ.get("GMM_SAMPLER_INTERVAL_S")
    os.environ.setdefault("GMM_SAMPLER_INTERVAL_S", "0.1")
    scraper = threading.Thread(target=_scraper, daemon=True)
    scraper.start()
    try:
        live_wall, live_res = timed(cfg_live)
    finally:
        stop.set()
        scraper.join(timeout=5.0)
        if sampler_env is None:
            os.environ.pop("GMM_SAMPLER_INTERVAL_S", None)

    def _openmetrics_ok(text: str) -> bool:
        if not text.rstrip().endswith("# EOF"):
            return False
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            parts = line.rsplit(" ", 1)
            if len(parts) != 2:
                return False
            try:
                float(parts[1])
            except ValueError:
                return False
        return True

    live_records = read_stream(cfg_live.metrics_file)
    spans = sum(1 for r in live_records if r.get("event") == "span")
    samples = sum(1 for r in live_records
                  if r.get("event") == "heartbeat" and r.get("sampler"))

    stream_overhead = stream_wall / max(off_wall, 1e-9)
    live_overhead = live_wall / max(off_wall, 1e-9)
    result = {
        "metric": f"live-plane overhead, {n}x{d} K={k} ({platform})",
        "value": round(live_overhead, 4),
        "unit": "x",
        # A/B ratio (live / off), NOT the NumPy baseline.
        "vs_baseline": round(live_overhead, 4),
        "accelerator_unavailable": accel_unavailable,
        "obs": {
            "n": n, "d": d, "k": k, "em_iters": iters,
            "chunk_size": chunk,
            "off_wall_s": round(off_wall, 4),
            "stream_wall_s": round(stream_wall, 4),
            "live_wall_s": round(live_wall, 4),
            "stream_overhead": round(stream_overhead, 4),
            "live_overhead": round(live_overhead, 4),
            "documented_bound": bound,
            "within_bound": bool(live_overhead <= bound),
            "scrapes": int(scrape["count"]),
            "scrape_parse_ok": bool(scrape["last"]
                                    and _openmetrics_ok(scrape["last"])),
            "span_records": int(spans),
            "sampler_heartbeats": int(samples),
            # The instrumentation must not change the arithmetic.
            "loglik_bit_identical": bool(
                off_res.final_loglik == stream_res.final_loglik
                == live_res.final_loglik),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_profile_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --profile mode: compile-introspection + cross-run diff contract.

    Runs the SAME fit twice (same data, same seed, same config, two
    telemetry streams) with the rev v2.2 CompileWatch active, then:

    * asserts the ``run_summary.profile`` block's SHAPE -- compiles /
      compile_seconds / xla_compiles / xla_compile_seconds present and
      coherent (site compiles <= XLA compiles, per-site counts sum to
      the total) -- the machine contract docs/OBSERVABILITY.md v2.2
      documents;
    * feeds both streams through ``gmm diff`` (telemetry.diff.diff_main,
      the same code path as the CLI) and records the exit code: two
      back-to-back identical runs MUST diff clean (``diff_exit == 0``;
      the default gates are count-shaped precisely so wall jitter
      cannot trip them).

    ``value`` is the first run's measured compile seconds (site builds).
    Size knobs: GMM_BENCH_PROFILE_{N,D,K,ITERS}.
    """
    import tempfile

    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("GMM_BENCH_PROFILE_N")
            or (200_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_PROFILE_D") or (16 if on_accel else 8))
    k = int(os.environ.get("GMM_BENCH_PROFILE_K") or (16 if on_accel else 8))
    iters = int(os.environ.get("GMM_BENCH_PROFILE_ITERS")
                or (10 if on_accel else 6))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.telemetry import read_stream
    from cuda_gmm_mpi_tpu.telemetry.diff import diff_main, summarize_run

    rng = np.random.default_rng(11)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)

    tmp = tempfile.mkdtemp(prefix="gmm-profile-")
    streams = [os.path.join(tmp, f"{name}.jsonl") for name in ("a", "b")]
    walls = []
    for path in streams:
        cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                        seed=0, metrics_file=path)
        t0 = time.perf_counter()
        fit_gmm(data, k, k, cfg)
        walls.append(time.perf_counter() - t0)

    def _profile_of(path):
        summaries = [r for r in read_stream(path)
                     if r.get("event") == "run_summary"]
        return (summaries[-1].get("profile") or {}) if summaries else {}

    profiles = [_profile_of(p) for p in streams]
    prof = profiles[0]
    site_total = sum(int((s or {}).get("compiles", 0))
                     for s in (prof.get("sites") or {}).values())
    shape_ok = bool(
        prof
        and isinstance(prof.get("compiles"), int)
        and isinstance(prof.get("xla_compiles"), int)
        and prof.get("compile_seconds") is not None
        and prof.get("xla_compile_seconds") is not None
        and prof["compiles"] <= prof["xla_compiles"]
        and site_total == prof["compiles"])

    diff_exit = diff_main([streams[0], streams[1]])
    rollup = summarize_run(read_stream(streams[0]))

    result = {
        "metric": f"compile seconds (profiled), {n}x{d} K={k} ({platform})",
        "value": round(float(prof.get("compile_seconds") or 0.0), 4),
        "unit": "s",
        # Identical back-to-back runs must diff clean: 1.0 = clean.
        "vs_baseline": 1.0 if diff_exit == 0 else 0.0,
        "accelerator_unavailable": accel_unavailable,
        "profile": {
            "n": n, "d": d, "k": k, "em_iters": iters,
            "chunk_size": chunk,
            "walls_s": [round(w, 4) for w in walls],
            "profile_shape_ok": shape_ok,
            "compiles": int(prof.get("compiles", 0)),
            "xla_compiles": int(prof.get("xla_compiles", 0)),
            "compile_seconds": float(prof.get("compile_seconds") or 0.0),
            "xla_compile_seconds": float(
                prof.get("xla_compile_seconds") or 0.0),
            "sites": {name: int((slot or {}).get("compiles", 0))
                      for name, slot in (prof.get("sites") or {}).items()},
            "cost_flops": (prof.get("cost") or {}).get("flops"),
            "cost_bytes_accessed": (prof.get("cost") or {}).get(
                "bytes_accessed"),
            "second_run_has_profile": bool(profiles[1]),
            "diff_exit": int(diff_exit),
            "fingerprint": rollup.get("fingerprint"),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_serve_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --serve mode: cold-vs-warm A/B of the serving subsystem.

    Fits a small mixture, exports it to a temporary model registry, and
    drives the in-process ``GMMServer`` (serving/server.py) with scoring
    requests of VARYING row counts:

      cold   the first request against an unwarmed server -- pays model
             load + AOT lower/compile of its (N-bucket, K-bucket)
             executable;
      warm   after one warm-up request per N-bucket, >= 100 requests
             whose row counts vary within the warmed buckets -- the
             steady state, where the zero-recompile contract says no
             request may trace or compile.

    ONE JSON record carries the cold first-request wall, the warm p50 /
    p99 / QPS, and the executor's compile counters before/after the warm
    phase (``zero_recompile_after_warm`` is the proof bit);
    ``vs_baseline`` is cold / warm-p50 -- what AOT caching saves every
    request after the first. Size knobs: GMM_BENCH_SERVE_{N,D,K,REQUESTS}
    (train rows, dims, clusters, warm request count).
    """
    on_accel = platform not in ("cpu",)
    k = int(os.environ.get("GMM_BENCH_SERVE_K") or (64 if on_accel else 8))
    n = int(os.environ.get("GMM_BENCH_SERVE_N")
            or (200_000 if on_accel else 4_000))
    d = int(os.environ.get("GMM_BENCH_SERVE_D") or (16 if on_accel else 4))
    n_requests = int(os.environ.get("GMM_BENCH_SERVE_REQUESTS") or 120)

    import tempfile

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.estimator import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import (GMMServer, ModelRegistry,
                                          ScoringExecutor)

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=5, max_iters=5,
                         chunk_size=min(65536, n)))
    gm.fit(data)

    with tempfile.TemporaryDirectory() as root:
        registry = ModelRegistry(root)
        gm.to_registry(registry, "bench")
        # A dedicated executor (not the process-shared one the fit above
        # may have warmed) so the cold number really is cold.
        executor = ScoringExecutor(min_block=256, max_block=4096)
        server = GMMServer(registry, executor=executor, warm=False)

        def request(i, rows):
            lo = rng.integers(0, n - rows)
            return {"id": int(i), "model": "bench", "op": "score_samples",
                    "x": data[lo:lo + rows].tolist()}

        # Cold: first request ever -- registry load + AOT compile + run.
        t0 = time.perf_counter()
        resp = server.handle_requests([request(0, 100)])[0]
        cold_s = time.perf_counter() - t0
        assert resp["ok"], resp
        # Warm-up: one request per N-bucket the warm phase will hit.
        sizes = [64, 100, 180, 250, 400, 900]
        for i, rows in enumerate(sizes):
            server.handle_requests([request(1000 + i, rows)])
        compiles_before = executor.compile_count
        lat = []
        t0 = time.perf_counter()
        for i in range(n_requests):
            rows = sizes[i % len(sizes)] + int(rng.integers(-30, 30))
            t1 = time.perf_counter()
            resp = server.handle_requests([request(i, max(rows, 2))])[0]
            lat.append(time.perf_counter() - t1)
            assert resp["ok"], resp
        warm_wall = time.perf_counter() - t0
        new_compiles = executor.compile_count - compiles_before
        lat = np.asarray(lat)
        p50 = float(np.percentile(lat, 50))
        p99 = float(np.percentile(lat, 99))

    result = {
        "metric": f"gmm serve warm p50 latency (K={k}, D={d}, {platform})",
        "value": round(p50, 6),
        "unit": "s",
        # Cold / warm-p50: what the AOT executable cache saves every
        # request after the first (NOT the NumPy baseline).
        "vs_baseline": round(cold_s / max(p50, 1e-9), 3),
        "accelerator_unavailable": accel_unavailable,
        "serve": {
            "train_n": n, "d": d, "k": k, "requests": n_requests,
            "cold_first_request_s": round(cold_s, 6),
            "warm": {
                "p50_s": round(p50, 6),
                "p99_s": round(p99, 6),
                "mean_s": round(float(lat.mean()), 6),
                "qps": round(n_requests / warm_wall, 2),
            },
            # The acceptance bit: after one warm-up per (model,
            # N-bucket), steady-state traffic with varying N performed
            # ZERO new traces/compiles.
            "new_compiles_after_warm": int(new_compiles),
            "zero_recompile_after_warm": bool(new_compiles == 0),
            "warm_p50_lt_cold": bool(p50 < cold_s),
            "executor": executor.stats(),
            # Resilience counters (stream rev v1.7): a soak run whose
            # server sheds, expires deadlines, trips breakers, or
            # hot-reloads surfaces that degradation in the artifact
            # instead of hiding it inside latency percentiles. A clean
            # A/B run reports all-zero.
            "resilience": server.resilience_stats(),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement of the serving path")
    return result


def _http_payload_window_ab(root: str, env: dict, repo: str) -> dict:
    """The rev v2.8 data-plane A/B riding ``--http``: the SAME D>=16
    batch traffic driven through two live single-process ``gmm serve
    --http`` servers -- arm A posts JSON bodies against a fixed
    ``--tick-ms`` gather window, arm B posts x-gmm-rows binary frames
    against the adaptive ``--tick-min-ms/--tick-max-ms`` controller.
    One record carries both p50/p99s plus:

    * ``parity`` -- the same probe rows scored via BOTH encodings on
      BOTH servers come back exactly equal (the zero-copy plane and the
      adaptive window are transport/scheduling changes, not math);
    * ``zero_recompile_after_warm`` -- per arm, every serve_batch past
      the warm phase dispatched with ``compiled == 0``;
    * ``host_staging`` -- per arm, the executor's host_stagings counter
      out of serve_summary (warm pinned-route traffic must read 0);
    * ``p50_ratio`` -- binary+adaptive p50 over json+fixed p50, and
      ``meets_target`` for the <= 0.7 acceptance line (the ratio is
      recorded either way).

    Size knobs: GMM_BENCH_HTTP_AB_{N,D,K,ROWS,REQUESTS}.
    """
    import signal
    import threading

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.estimator import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import GMMClient, ModelRegistry

    k = int(os.environ.get("GMM_BENCH_HTTP_AB_K") or 8)
    d = int(os.environ.get("GMM_BENCH_HTTP_AB_D") or 16)
    n = int(os.environ.get("GMM_BENCH_HTTP_AB_N") or 4_000)
    rows = int(os.environ.get("GMM_BENCH_HTTP_AB_ROWS") or 256)
    n_requests = int(os.environ.get("GMM_BENCH_HTTP_AB_REQUESTS") or 120)
    warm_requests = 10
    n_clients = 2

    rng = np.random.default_rng(7)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d)))
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=5, max_iters=5,
                         chunk_size=min(65536, n)))
    gm.fit(data)
    reg_dir = os.path.join(root, "ab_reg")
    gm.to_registry(ModelRegistry(reg_dir), "ab")

    payloads = [np.ascontiguousarray(data[i * rows:(i + 1) * rows])
                for i in range(8)]
    payloads_json = [p.tolist() for p in payloads]
    probe = payloads[0]

    arms = (
        ("json_fixed", "json", ["--tick-ms", "2"]),
        ("binary_adaptive", "binary",
         ["--tick-ms", "2", "--tick-min-ms", "0", "--tick-max-ms", "2"]),
    )
    out: dict = {"d": d, "k": k, "rows_per_request": rows,
                 "requests": n_requests, "clients": n_clients}
    parity_results: dict = {}
    for arm, enc, extra in arms:
        port_file = os.path.join(root, f"ab_{arm}.port")
        metrics_file = os.path.join(root, f"ab_{arm}.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "serve",
             "--registry", reg_dir, "--http", "0",
             "--http-port-file", port_file, "--device", "cpu",
             "--metrics-file", metrics_file, *extra],
            cwd=repo, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            t0 = time.perf_counter()
            while not os.path.exists(port_file):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"ab arm {arm} exited rc={proc.returncode} "
                        "before publishing its port")
                if time.perf_counter() - t0 > 300:
                    raise RuntimeError(f"ab arm {arm} startup timed out")
                time.sleep(0.05)
            with open(port_file) as f:
                port = int(f.read())
            client = GMMClient(f"127.0.0.1:{port}", timeout_s=60.0,
                               retries=2, backoff_base_s=0.05,
                               encoding=enc)

            counter = {"next": 0}
            lock = threading.Lock()
            lat: list = []

            def drive(budget: int, timed: bool):
                def take() -> bool:
                    with lock:
                        if counter["next"] >= budget:
                            return False
                        counter["next"] += 1
                        return True
                i = 0
                while take():
                    i += 1
                    x = (payloads[i % len(payloads)] if enc == "binary"
                         else payloads_json[i % len(payloads)])
                    t1 = time.perf_counter()
                    client.request("ab", "score_samples", x)
                    if timed:
                        with lock:
                            lat.append(time.perf_counter() - t1)

            def run_phase(budget: int, timed: bool) -> None:
                counter["next"] = 0
                threads = [threading.Thread(target=drive,
                                            args=(budget, timed),
                                            daemon=True)
                           for _ in range(n_clients)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()

            # Warm under the SAME concurrency as the timed phase so
            # both the solo and the coalesced row buckets compile now.
            run_phase(warm_requests, timed=False)
            # Parity probes: the same rows via BOTH encodings on THIS
            # server must score exactly equal.
            parity_results[arm] = (
                client.request("ab", "score_samples", probe.tolist(),
                               encoding="json")["result"],
                client.request("ab", "score_samples", probe,
                               encoding="binary")["result"])
            warm_rows = (warm_requests + 2) * rows
            t_load = time.perf_counter()
            run_phase(n_requests, timed=True)
            load_wall = time.perf_counter() - t_load
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        compiled_after_warm = 0
        seen_rows = 0
        host_stagings = None
        window = None
        adaptations = 0
        with open(metrics_file) as f:
            for line in f:
                rec = json.loads(line)
                ev = rec.get("event")
                if ev == "serve_batch":
                    if seen_rows >= warm_rows:
                        compiled_after_warm += int(rec.get("compiled", 0))
                    seen_rows += int(rec.get("rows", 0))
                elif ev == "serve_window":
                    adaptations += 1
                elif ev == "serve_summary":
                    ex = rec.get("executor") or {}
                    host_stagings = ex.get("host_stagings")
                    window = rec.get("window")
        lat_arr = np.asarray(sorted(lat))
        out[arm] = {
            "encoding": enc,
            "p50_s": round(float(np.percentile(lat_arr, 50)), 6),
            "p99_s": round(float(np.percentile(lat_arr, 99)), 6),
            "qps": round(len(lat) / max(load_wall, 1e-9), 2),
            # Warm pinned-route traffic must never stage host-side.
            "host_staging": host_stagings,
            "compiled_after_warm": int(compiled_after_warm),
            "zero_recompile_after_warm": bool(compiled_after_warm == 0),
            **({"window_adaptations": adaptations, "window": window}
               if arm == "binary_adaptive" else {}),
        }

    # The parity bit: every probe answer -- json vs binary, fixed vs
    # adaptive -- is exactly the same floats.
    flat = [r for pair in parity_results.values() for r in pair]
    parity = all(r == flat[0] for r in flat[1:])
    assert parity, "payload/window A/B parity broke: " \
        f"{[r[:2] for r in flat]}"
    ratio = (out["binary_adaptive"]["p50_s"]
             / max(out["json_fixed"]["p50_s"], 1e-9))
    out["parity"] = bool(parity)
    out["p50_ratio"] = round(ratio, 3)
    out["meets_target"] = bool(ratio <= 0.7)
    return out


def run_http_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --http mode: rev v2.7 network-tier contract, measured live.

    Fits + exports a small mixture, launches a REAL ``gmm serve --http 0
    --workers W`` subprocess tree (HTTP front end + supervised worker
    pool over TCP), and drives it closed-loop with C concurrent
    :class:`GMMClient` threads. Mid-load (~40% through), one worker
    process is SIGKILLed and the load keeps running -- the acceptance
    contract is that the pool's sibling retry + supervised respawn hide
    the kill from every client (``zero_failed_requests``). The record
    carries the TCP warm p50/p99/QPS, the kill->respawned recovery
    wall, client retry counters, the drain exit code (SIGTERM must
    yield 75/EX_TEMPFAIL), and the server's own ``serve_summary.http``
    rollup. ``vs_baseline`` is TCP p50 / in-process p50 on the same
    model and row count -- what the network + pool tier costs per
    request. Workers always run on CPU (N subprocesses must not fight
    over one accelerator tunnel), so the sizes stay small; this mode
    measures the tier, not the kernel. The record also carries the rev
    v2.8 data-plane A/B (``http.ab``): json+fixed-tick vs
    binary+adaptive-window on identical D>=16 batch traffic, with the
    parity bit and per-arm zero-recompile/host-staging proof
    (:func:`_http_payload_window_ab`). Size knobs:
    GMM_BENCH_HTTP_{N,D,K,WORKERS,CLIENTS,REQUESTS} and
    GMM_BENCH_HTTP_AB_{N,D,K,ROWS,REQUESTS}.
    """
    k = int(os.environ.get("GMM_BENCH_HTTP_K") or 8)
    n = int(os.environ.get("GMM_BENCH_HTTP_N") or 4_000)
    d = int(os.environ.get("GMM_BENCH_HTTP_D") or 4)
    n_requests = int(os.environ.get("GMM_BENCH_HTTP_REQUESTS") or 200)
    n_workers = int(os.environ.get("GMM_BENCH_HTTP_WORKERS") or 2)
    n_clients = int(os.environ.get("GMM_BENCH_HTTP_CLIENTS") or 4)
    rows = 100

    import signal
    import tempfile
    import threading

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.estimator import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import (GMMClient, GMMClientError,
                                          GMMServer, ModelRegistry)

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=5, max_iters=5,
                         chunk_size=min(65536, n)))
    gm.fit(data)

    def body(i):
        lo = int(rng.integers(0, n - rows))
        return data[lo:lo + rows].tolist()

    with tempfile.TemporaryDirectory() as root:
        reg_dir = os.path.join(root, "reg")
        registry = ModelRegistry(reg_dir)
        gm.to_registry(registry, "bench")

        # In-process baseline: the same registry + op behind zero
        # network, warmed; TCP p50 / this p50 is the tier's unit cost.
        server = GMMServer(ModelRegistry(reg_dir), warm=False)
        for i in range(3):
            server.handle_requests([{"id": i, "model": "bench",
                                     "op": "score_samples",
                                     "x": body(i)}])
        base_lat = []
        for i in range(30):
            t1 = time.perf_counter()
            resp = server.handle_requests(
                [{"id": i, "model": "bench", "op": "score_samples",
                  "x": body(i)}])[0]
            base_lat.append(time.perf_counter() - t1)
            assert resp["ok"], resp
        inproc_p50 = float(np.percentile(np.asarray(base_lat), 50))

        port_file = os.path.join(root, "port.txt")
        worker_dir = os.path.join(root, "wd")
        metrics_file = os.path.join(root, "serve.jsonl")
        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        proc = subprocess.Popen(
            [sys.executable, "-m", "cuda_gmm_mpi_tpu.cli", "serve",
             "--registry", reg_dir, "--http", "0",
             "--workers", str(n_workers), "--http-port-file", port_file,
             "--worker-dir", worker_dir, "--device", "cpu",
             "--metrics-file", metrics_file,
             "--worker-backoff-s", "0.2"],
            cwd=repo, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            t0 = time.perf_counter()
            while not os.path.exists(port_file):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"gmm serve --http exited rc={proc.returncode} "
                        "before publishing its port")
                if time.perf_counter() - t0 > 300:
                    raise RuntimeError("gmm serve --http startup timed out")
                time.sleep(0.05)
            startup_s = time.perf_counter() - t0
            with open(port_file) as f:
                port = int(f.read())

            client = GMMClient(f"127.0.0.1:{port}", timeout_s=60.0,
                               retries=3, backoff_base_s=0.05,
                               retry_budget=0.5)
            for i in range(2 * n_workers):  # warm every worker's caches
                client.request("bench", "score_samples", body(i))

            # Pre-drawn payloads: the shared numpy Generator is not
            # thread-safe, so the driver threads index a fixed set.
            payloads = [body(i) for i in range(16)]
            counter = {"next": 0, "failed": 0}
            lock = threading.Lock()
            lat: list = []
            kill = {"at": int(n_requests * 0.4) if n_workers >= 2
                    else None, "t_kill": None, "recovery_s": None,
                    "pid": None}

            def take() -> bool:
                with lock:
                    if counter["next"] >= n_requests:
                        return False
                    counter["next"] += 1
                    return True

            def drive():
                i = 0
                while take():
                    i += 1
                    t1 = time.perf_counter()
                    try:
                        client.request("bench", "score_samples",
                                       payloads[i % len(payloads)])
                        with lock:
                            lat.append(time.perf_counter() - t1)
                    except GMMClientError:
                        with lock:
                            counter["failed"] += 1

            def killer():
                # SIGKILL worker 0 mid-load, then clock the supervised
                # respawn: kill -> new pid in worker0.json + live socket.
                while True:
                    with lock:
                        if counter["next"] >= kill["at"]:
                            break
                    time.sleep(0.002)
                state = os.path.join(worker_dir, "worker0.json")
                with open(state) as f:
                    w0 = json.load(f)
                kill["pid"] = w0["pid"]
                kill["t_kill"] = time.perf_counter()
                os.kill(w0["pid"], signal.SIGKILL)
                deadline = time.perf_counter() + 120
                while time.perf_counter() < deadline:
                    try:
                        with open(state) as f:
                            cur = json.load(f)
                        if (cur["pid"] != w0["pid"]
                                and os.path.exists(cur["socket"])):
                            kill["recovery_s"] = (time.perf_counter()
                                                  - kill["t_kill"])
                            return
                    except (OSError, ValueError, KeyError):
                        pass
                    time.sleep(0.01)

            threads = [threading.Thread(target=drive, daemon=True)
                       for _ in range(n_clients)]
            kt = None
            if kill["at"] is not None:
                kt = threading.Thread(target=killer, daemon=True)
                kt.start()
            t_load = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            load_wall = time.perf_counter() - t_load
            if kt is not None:
                kt.join(timeout=130)

            proc.send_signal(signal.SIGTERM)
            drain_rc = proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        rollup = None
        try:
            with open(metrics_file) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("event") == "serve_summary":
                        rollup = rec.get("http")
        except OSError:
            pass

        # Payload-format x window-policy A/B (rev v2.8): json+fixed
        # vs binary+adaptive on identical D>=16 batch traffic.
        ab = _http_payload_window_ab(root, env, repo)

    lat_arr = np.asarray(sorted(lat))
    p50 = float(np.percentile(lat_arr, 50)) if lat_arr.size else 0.0
    p99 = float(np.percentile(lat_arr, 99)) if lat_arr.size else 0.0
    failed = counter["failed"]
    result = {
        "metric": (f"gmm serve --http warm p50 latency over TCP "
                   f"(K={k}, D={d}, {n_workers} workers, cpu)"),
        "value": round(p50, 6),
        "unit": "s",
        # TCP p50 / in-process p50: the network + pool tier's unit cost.
        "vs_baseline": round(p50 / max(inproc_p50, 1e-9), 3),
        "accelerator_unavailable": accel_unavailable,
        "http": {
            "train_n": n, "d": d, "k": k, "rows_per_request": rows,
            "workers": n_workers, "clients": n_clients,
            "requests": n_requests, "startup_s": round(startup_s, 3),
            "p50_s": round(p50, 6), "p99_s": round(p99, 6),
            "qps": round(len(lat) / max(load_wall, 1e-9), 2),
            "inproc_p50_s": round(inproc_p50, 6),
            # The acceptance bit: a SIGKILLed worker mid-load cost ZERO
            # failed client requests (sibling retry + respawn hid it).
            "failed_requests": int(failed),
            "zero_failed_requests": bool(failed == 0),
            "worker_killed": bool(kill["t_kill"] is not None),
            "kill_recovery_s": (round(kill["recovery_s"], 3)
                                if kill["recovery_s"] is not None
                                else None),
            "client": client.stats(),
            # SIGTERM drain over TCP keeps the preemption contract.
            "drain_exit_code": int(drain_rc),
            "clean_drain_exit_75": bool(drain_rc == 75),
            # The server's own serve_summary.http rollup, verbatim.
            "rollup": rollup,
            # json+fixed-tick vs binary+adaptive-window, same traffic.
            "ab": ab,
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); the http "
            "tier always measures CPU workers, so this note only "
            "records how the session got here")
    return result


def run_drift_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --drift mode: rev v2.4 serve-time drift-detection contract.

    Fits a small mixture (its training envelope lands in the registry
    export), serves it with the drift plane enabled, and replays two
    traffic phases -- rows drawn from the TRAINING data, then the same
    rows with a deliberate mean shift -- flushing one drift window after
    each. The contract under test:

    * psi_in (in-distribution window) stays under the alarm threshold
      and psi_shifted (shifted window) lands over it -- the detector
      separates the phases;
    * the shifted window raised a ``drift_alarm`` (observational: the
      breaker stays untouched);
    * drift-on steady-state serving costs ~ the same wall as drift-off
      on identical warmed traffic (``vs_baseline`` is that ratio): the
      plane folds in the request's own 'proba' block, no extra
      dispatches.

    Size knobs: GMM_BENCH_DRIFT_{N,D,K,REQUESTS}.
    """
    on_accel = platform not in ("cpu",)
    k = int(os.environ.get("GMM_BENCH_DRIFT_K") or (16 if on_accel else 8))
    n = int(os.environ.get("GMM_BENCH_DRIFT_N")
            or (100_000 if on_accel else 4_000))
    d = int(os.environ.get("GMM_BENCH_DRIFT_D") or (8 if on_accel else 4))
    n_requests = int(os.environ.get("GMM_BENCH_DRIFT_REQUESTS") or 80)
    threshold = 0.2

    import tempfile

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.estimator import GaussianMixture
    from cuda_gmm_mpi_tpu.serving import (GMMServer, ModelRegistry,
                                          ScoringExecutor)

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=5, max_iters=5,
                         chunk_size=min(65536, n)))
    gm.fit(data)

    def request(i, rows, shift=0.0):
        lo = rng.integers(0, n - rows)
        x = data[lo:lo + rows] + np.float32(shift)
        return {"id": int(i), "model": "bench", "op": "score_samples",
                "x": x.tolist()}

    with tempfile.TemporaryDirectory() as root:
        registry = ModelRegistry(root)
        gm.to_registry(registry, "bench")
        envelope_ok = registry.load_envelope("bench") is not None

        executor = ScoringExecutor(min_block=256, max_block=4096)
        sizes = [64, 100, 180, 250]

        def replay(server, phase_shift, count):
            t0 = time.perf_counter()
            for i in range(count):
                rows = sizes[i % len(sizes)]
                resp = server.handle_requests(
                    [request(i, rows, phase_shift)])[0]
                assert resp["ok"], resp
            return time.perf_counter() - t0

        # Drift-off baseline: same registry, same (pre-warmed after the
        # first replay) executor, drift plane fully disabled.
        server_off = GMMServer(registry, executor=executor, warm=False)
        replay(server_off, 0.0, len(sizes))  # warm every N-bucket
        wall_off = replay(server_off, 0.0, n_requests)

        # Drift-on server: huge interval so the timer never fires
        # mid-phase -- windows are flushed explicitly per phase.
        server_on = GMMServer(registry, executor=executor, warm=False,
                              drift_interval_s=3600.0,
                              drift_psi_threshold=threshold)
        replay(server_on, 0.0, len(sizes))
        server_on.flush_drift()  # discard the warm-up window
        compiles_before = executor.compile_count

        wall_on = replay(server_on, 0.0, n_requests)
        rows_in = server_on.flush_drift()
        wall_shifted = replay(server_on, 6.0, n_requests)
        rows_shifted = server_on.flush_drift()
        new_compiles = executor.compile_count - compiles_before

    psi_in = rows_in[0]["psi"] if rows_in else None
    psi_shifted = rows_shifted[0]["psi"] if rows_shifted else None
    alarm_in = bool(rows_in and rows_in[0]["alarm"])
    alarm_shifted = bool(rows_shifted and rows_shifted[0]["alarm"])
    overhead = wall_on / max(wall_off, 1e-9)
    detected = bool(psi_in is not None and psi_shifted is not None
                    and not alarm_in and alarm_shifted
                    and psi_shifted > psi_in)
    result = {
        "metric": f"serve drift-plane overhead (K={k}, D={d}, "
                  f"{platform})",
        "value": round(overhead, 4),
        "unit": "x",
        # Drift-on / drift-off wall on identical warmed traffic (NOT the
        # NumPy baseline): ~1.0 = the plane is free, as designed.
        "vs_baseline": round(overhead, 4),
        "accelerator_unavailable": accel_unavailable,
        "drift": {
            "train_n": n, "d": d, "k": k, "requests": n_requests,
            "threshold": threshold,
            "envelope_in_registry": envelope_ok,
            "psi_in": psi_in,
            "psi_shifted": psi_shifted,
            "alarm_in": alarm_in,
            "alarm_fired": alarm_shifted,
            "detected": detected,
            "wall_off_s": round(wall_off, 4),
            "wall_on_s": round(wall_on, 4),
            "wall_shifted_s": round(wall_shifted, 4),
            "overhead": round(overhead, 4),
            # Drift sampling must stay on the answered block: zero new
            # executor compiles across both drift-on phases.
            "new_compiles": int(new_compiles),
            "zero_recompile": bool(new_compiles == 0),
            "drift_stats": server_on.drift_stats(),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement of the drift plane")
    return result


def run_lifecycle_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --lifecycle mode: rev v2.6 closed-loop lifecycle contract.

    One record drives the entire loop end to end against an in-process
    server with the drift plane and a bound LifecycleController:

    * injected drift traffic raises the alarm and schedules a retrain;
    * the shadow minibatch-EM refit publishes an invisible candidate,
      the canary gates (PSI/KS/mean-regression on the holdout slice)
      pass, and the duplicate-dispatch shadow window scores live ticks
      under BOTH versions with zero client-visible change;
    * promotion flips the candidate live atomically via the existing
      hot-reload path;
    * injected post-promotion traffic from a worse distribution trips
      the watch score gate and rolls back to the pinned prior version,
      quarantining the bad candidate;
    * ``rollback_restored_bit_identical``: every npz leaf of the
      restored version equals the pre-promotion version's, AND a fixed
      probe request scores byte-identically against the rolled-back
      server vs the pre-promotion server.

    ``vs_baseline`` is the lifecycle-on / lifecycle-off steady-serve
    wall ratio on identical warmed traffic (idle controller): the
    controller rides the tick loop, so ~1.0 is the design point.

    Size knobs: GMM_BENCH_LIFECYCLE_{N,D,K,REQUESTS}.
    """
    on_accel = platform not in ("cpu",)
    k = int(os.environ.get("GMM_BENCH_LIFECYCLE_K")
            or (16 if on_accel else 4))
    n = int(os.environ.get("GMM_BENCH_LIFECYCLE_N")
            or (100_000 if on_accel else 4_000))
    d = int(os.environ.get("GMM_BENCH_LIFECYCLE_D")
            or (8 if on_accel else 4))
    n_requests = int(os.environ.get("GMM_BENCH_LIFECYCLE_REQUESTS") or 40)

    import tempfile

    from cuda_gmm_mpi_tpu import telemetry
    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.estimator import GaussianMixture
    from cuda_gmm_mpi_tpu.lifecycle import (LifecycleController,
                                            LifecyclePolicy)
    from cuda_gmm_mpi_tpu.serving import GMMServer, ModelRegistry

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)
    gm = GaussianMixture(
        k, target_components=k,
        config=GMMConfig(min_iters=5, max_iters=5,
                         chunk_size=min(65536, n)))
    gm.fit(data)

    def traffic(server, shift, requests, rows=40, start=0):
        t0 = time.perf_counter()
        for i in range(requests):
            lo = ((start + i) * 17) % (n - rows)
            x = (data[lo:lo + rows] + np.float32(shift)).tolist()
            resp = server.handle_requests(
                [{"id": int(i), "model": "bench",
                  "op": "score_samples", "x": x}])[0]
            assert resp["ok"], resp
        return time.perf_counter() - t0

    probe_x = data[:64].tolist()

    def probe(server):
        resp = server.handle_requests(
            [{"id": 0, "model": "bench", "op": "score_samples",
              "x": probe_x}])[0]
        assert resp["ok"], resp
        return resp["result"]

    stream = []

    class _Sink:
        def write(self, line):
            stream.append(json.loads(line))

        def flush(self):
            pass

    policy = LifecyclePolicy({
        "debounce_alarms": 1,
        "cooldown_s": 600.0,
        "holdout_rows": 256,
        "retrain": {"steps": 4, "min_rows": 64,
                    "chunk_size": min(4096, n)},
        # A drift-adapting candidate legitimately scores the drifted
        # holdout very differently from the incumbent -- the bench
        # widens the distribution gates and keeps the regression gate.
        "canary": {"max_psi": 100.0, "max_ks": 1.0, "shadow_ticks": 2},
        "watch": {"probation_ticks": 64, "probation_s": 600.0,
                  "min_rows": 32},
    })

    with tempfile.TemporaryDirectory() as root:
        registry = ModelRegistry(root)
        gm.to_registry(registry, "bench")

        # Lifecycle-off baseline on identical warmed traffic.
        server_off = GMMServer(registry, warm=False,
                               drift_interval_s=3600.0,
                               drift_psi_threshold=0.2)
        traffic(server_off, 0.0, 4)  # warm
        wall_off = traffic(server_off, 0.0, n_requests)

        ctl = LifecycleController(registry, policy)
        server = GMMServer(registry, warm=False,
                           drift_interval_s=3600.0,
                           drift_psi_threshold=0.2, lifecycle=ctl)
        traffic(server, 0.0, 4)  # warm
        wall_on = traffic(server, 0.0, n_requests)  # controller idle
        server.flush_drift()  # discard the in-distribution window

        rec = telemetry.RunRecorder(stream=_Sink())
        with telemetry.use(rec), rec:
            probe_before = probe(server)  # pre-promotion scoring pin

            # Phase 1: injected drift -> alarm -> retrain scheduled.
            t0 = time.perf_counter()
            traffic(server, 6.0, n_requests)
            drift_rows = server.flush_drift()
            wall_drift = time.perf_counter() - t0
            alarm_fired = bool(drift_rows and drift_rows[0]["alarm"])

            # Phase 2: shadow refit + candidate publish + canary gates.
            t0 = time.perf_counter()
            ctl.on_tick()
            wall_retrain = time.perf_counter() - t0

            # Phase 3: duplicate-dispatch shadow window, then the tick
            # that closes the canary and promotes.
            t0 = time.perf_counter()
            traffic(server, 6.0, max(2, policy.canary["shadow_ticks"]),
                    start=1000)
            ctl.on_tick()
            wall_canary = time.perf_counter() - t0
            promoted_version = server.resolve("bench").version

            # Phase 4: injected post-promotion regression (traffic from
            # a far-worse distribution) -> watch violation -> rollback.
            t0 = time.perf_counter()
            traffic(server, 40.0, 4, start=2000)
            ctl.on_tick()
            wall_rollback = time.perf_counter() - t0

            probe_after = probe(server)  # post-rollback scoring

        counts = dict(ctl.counts)
        live = registry.versions("bench")
        restored_version = live[-1] if live else None
        prior = registry.load("bench", 1)
        restored = registry.load("bench", int(restored_version))
        leaves_equal = all(
            np.array_equal(np.asarray(getattr(prior.state, f)),
                           np.asarray(getattr(restored.state, f)))
            for f in ("means", "pi", "R", "Rinv", "N", "active",
                      "avgvar", "constant")
        ) and np.array_equal(np.asarray(prior.data_shift),
                             np.asarray(restored.data_shift))
        bit_identical = bool(leaves_equal and probe_before == probe_after)

    lc = [e for e in stream if e.get("event") == "lifecycle"]
    canary_pass = next((e for e in lc if e["phase"] == "canary"
                        and e.get("outcome") == "pass"), {})
    rollbacks = [e for e in lc if e["phase"] == "rollback"]
    overhead = wall_on / max(wall_off, 1e-9)
    closed_loop = bool(
        alarm_fired and counts["retrains"] == 1
        and counts["promotes"] == 1 and counts["rollbacks"] == 1
        and counts["quarantines"] == 1 and bit_identical)
    result = {
        "metric": f"closed-loop lifecycle serve overhead (K={k}, D={d}, "
                  f"{platform})",
        "value": round(overhead, 4),
        "unit": "x",
        # Lifecycle-on / lifecycle-off steady serve wall on identical
        # warmed traffic: the controller rides the tick loop, ~1.0.
        "vs_baseline": round(overhead, 4),
        "accelerator_unavailable": accel_unavailable,
        "lifecycle": {
            "train_n": n, "d": d, "k": k, "requests": n_requests,
            "alarm_fired": alarm_fired,
            "phases": {
                "drift_detect_s": round(wall_drift, 4),
                "retrain_s": round(wall_retrain, 4),
                "canary_promote_s": round(wall_canary, 4),
                "rollback_s": round(wall_rollback, 4),
            },
            "gates": {kk: canary_pass.get(kk)
                      for kk in ("psi", "ks", "mean_incumbent",
                                 "mean_candidate", "regression",
                                 "tolerance", "shadow_rows",
                                 "shadow_ticks")},
            "promoted_version": int(promoted_version),
            "rollback_reason": (rollbacks[-1].get("reason")
                                if rollbacks else None),
            "restored_version": (int(restored_version)
                                 if restored_version else None),
            "live_versions": [int(v) for v in live],
            "rollback_restored_bit_identical": bit_identical,
            "counts": counts,
            "wall_off_s": round(wall_off, 4),
            "wall_on_s": round(wall_on, 4),
            "overhead": round(overhead, 4),
            "closed_loop": closed_loop,
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement of the lifecycle loop")
    return result


def run_timeline_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --timeline mode: rev v2.3 Perfetto trace-export contract.

    Runs ONE fit with the live observability plane active (metrics_port=0
    -> trace spans + clock-anchored heartbeats on the stream), exports
    the stream through ``telemetry.timeline.build_timeline`` -- the same
    code path as ``gmm timeline`` -- and holds the result to the export's
    own contract:

    * the emitted document passes ``validate_trace`` (the ``--validate``
      structural oracle: known phases, nonnegative X durations,
      per-track timestamp order, flow pairing, nonzero events);
    * alignment mode is ``clock`` (a v2.3 recorder MUST anchor its own
      stream; ``estimated`` here means the clock pairs went missing);
    * the trace actually carries slices (spans + em_iter) and counter
      samples, not just instants.

    ``value`` is the export wall (build + write + reload + validate).
    Size knobs: GMM_BENCH_TIMELINE_{N,D,K,ITERS}.
    """
    import json as json_mod
    import tempfile

    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("GMM_BENCH_TIMELINE_N")
            or (200_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_TIMELINE_D") or (16 if on_accel else 8))
    k = int(os.environ.get("GMM_BENCH_TIMELINE_K") or (16 if on_accel else 8))
    iters = int(os.environ.get("GMM_BENCH_TIMELINE_ITERS")
                or (10 if on_accel else 6))
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    chunk = min(chunk, n)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.telemetry.timeline import (
        build_timeline, summarize_trace, validate_trace)

    rng = np.random.default_rng(13)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (centers[rng.integers(0, k, n)]
            + rng.normal(scale=1.0, size=(n, d))).astype(np.float32)

    tmp = tempfile.mkdtemp(prefix="gmm-timeline-")
    stream = os.path.join(tmp, "fit.jsonl")
    cfg = GMMConfig(min_iters=iters, max_iters=iters, chunk_size=chunk,
                    seed=0, metrics_file=stream, metrics_port=0)
    t0 = time.perf_counter()
    fit_gmm(data, k, k, cfg)
    fit_wall = time.perf_counter() - t0

    out = os.path.join(tmp, "fit.trace.json")
    t0 = time.perf_counter()
    doc = build_timeline([stream])
    with open(out, "w", encoding="utf-8") as fh:
        json_mod.dump(doc, fh)
    with open(out, "r", encoding="utf-8") as fh:
        reloaded = json_mod.load(fh)
    errors = validate_trace(reloaded)
    export_wall = time.perf_counter() - t0

    summary = summarize_trace(reloaded)
    validate_ok = not errors
    clean = bool(validate_ok
                 and summary["alignment"] == "clock"
                 and summary["slices"] > 0
                 and summary["counters"] > 0)

    result = {
        "metric": f"timeline export wall, {n}x{d} K={k} ({platform})",
        "value": round(export_wall, 4),
        "unit": "s",
        # Export must validate clean with clock alignment: 1.0 = clean.
        "vs_baseline": 1.0 if clean else 0.0,
        "accelerator_unavailable": accel_unavailable,
        "timeline": {
            "n": n, "d": d, "k": k, "em_iters": iters,
            "chunk_size": chunk,
            "fit_wall_s": round(fit_wall, 4),
            "export_wall_s": round(export_wall, 4),
            "events": summary["events"],
            "slices": summary["slices"],
            "counters": summary["counters"],
            "instants": summary["instants"],
            "flows": summary["flows"],
            "tracks": summary["tracks"],
            "alignment": summary["alignment"],
            "validate_ok": validate_ok,
            "validate_errors": len(errors),
            "trace_bytes": os.path.getsize(out),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def run_ingest_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --ingest mode: host-resident vs pipelined out-of-core A/B.

    Writes one BIN dataset to a temp dir, then fits it three ways, each in
    its OWN subprocess so ``ru_maxrss`` (a process-lifetime high-water
    mark) isolates per-mode peak host memory:

      resident    --stream-events with the whole slice materialized up
                  front (the pre-round-13 path);
      pipelined   --ingest=pipelined -- blocks prefetched from disk on a
                  background thread, peak host memory O(queue x block);
      minibatch   --ingest=pipelined --em-mode=minibatch -- stepwise EM,
                  each step touching one minibatch of blocks.

    ONE JSON record carries all three walls, per-mode peak RSS and RSS
    growth (peak minus the post-import/post-device-init baseline, so the
    jax runtime's fixed footprint cancels out of the comparison), the
    resident==pipelined loglik parity BIT (exact equality -- the
    bit-identity contract, not a tolerance), and the minibatch loglik with
    its REGRESSION vs full EM (worse-than-full only; a stepwise endpoint
    that lands past the full-EM one scores zero) against the acceptance
    bound ``health_regression_scale x convergence_epsilon(n, d)`` (the
    minibatch side runs a gamma-sum-matched step budget so both endpoints
    are converged). ``vs_baseline`` is the RSS-growth ratio
    resident / pipelined -- the memory headline; walls are expected
    comparable (the device does the same math; prefetch hides the read
    latency). Size knobs: GMM_BENCH_INGEST_{N,D,K,BLOCK} (events, dims,
    clusters, chunk size), GMM_BENCH_INGEST_ITERS.
    """
    import subprocess
    import tempfile

    on_accel = platform not in ("cpu",)
    # Default N is sized so the DATA dominates the jax runtime's ~160 MB
    # fixed allocations: at small N both modes' RSS growth is all runtime
    # and the ratio flattens to ~1 regardless of ingestion mode.
    n = int(os.environ.get("GMM_BENCH_INGEST_N")
            or (8_000_000 if on_accel else 4_000_000))
    d = int(os.environ.get("GMM_BENCH_INGEST_D") or (16 if on_accel else 8))
    k = int(os.environ.get("GMM_BENCH_INGEST_K") or 8)
    block = int(os.environ.get("GMM_BENCH_INGEST_BLOCK")
                or (65536 if on_accel else 4096))
    # 15 full-EM iterations converge the synthetic blob data on both
    # platforms; the minibatch A/B side matches this budget in
    # gamma-sum-effective iterations, so its within-tolerance claim
    # compares two CONVERGED endpoints. Override for quick runs at the
    # cost of that claim.
    iters = int(os.environ.get("GMM_BENCH_INGEST_ITERS") or 15)
    block = min(block, n)

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, d))

    def write_chunked(path):
        # Generate straight to disk in bounded slices: the parent's RSS at
        # fork time is COW-inherited into each child's ru_maxrss high-water
        # mark, so a parent that materialized the dataset would poison
        # every child's baseline and flatten the growth comparison to 0.
        step = 1 << 16
        with open(path, "wb") as f:
            np.asarray([n, d], np.int32).tofile(f)
            for lo in range(0, n, step):
                m = min(step, n - lo)
                xb = (centers[rng.integers(0, k, m)]
                      + rng.normal(scale=1.0, size=(m, d)))
                xb.astype(np.float32).tofile(f)

    # Each mode runs in a child so ru_maxrss is per-mode, and the child
    # snapshots its baseline AFTER jax device init: growth = data path only.
    child = r"""
import json, resource, sys, time
path, mode, k, block, iters = (sys.argv[1], sys.argv[2], int(sys.argv[3]),
                               int(sys.argv[4]), int(sys.argv[5]))
import jax
jax.config.update("jax_enable_x64", True)
jax.devices()
from cuda_gmm_mpi_tpu.config import GMMConfig
from cuda_gmm_mpi_tpu.io import FileSource
from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
base_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
steps = iters
if mode == "minibatch":
    # Stepwise EM moves the running estimate by gamma_t per step, so a
    # T-step run covers ~ 1 + sum_{t>=1} (t + t0)^-alpha full-EM-equivalent
    # iterations. Match the full run's budget plus margin, so the A/B
    # compares like-for-like optimization effort; the within-tolerance
    # claim additionally needs GMM_BENCH_INGEST_ITERS high enough that
    # full EM itself has converged (the default is).
    eff_target = iters + 3
    eff = 1.0
    steps = 1
    while eff < eff_target:
        eff += (steps + 2.0) ** -0.7
        steps += 1
cfg = GMMConfig(
    # float64: at N in the millions, float32 summation noise alone
    # (~1e-6 relative) would swamp the minibatch-vs-full tolerance,
    # turning the A/B into a rounding measurement.
    stream_events=True, chunk_size=block, seed=11, dtype="float64",
    min_iters=steps, max_iters=steps,
    ingest=("resident" if mode == "resident" else "pipelined"),
    em_mode=("minibatch" if mode == "minibatch" else "full"),
    # 16 blocks per step: the stepwise endpoint's loglik deficit scales
    # ~ gamma_T / batch_size (per-batch statistics noise through the
    # decayed average), so the batch is sized to land the deficit well
    # inside the health tolerance at the default N.
    minibatch_size=(16 * block if mode == "minibatch" else 0))
t0 = time.perf_counter()
res = fit_gmm(FileSource(path), k, k, cfg)
wall = time.perf_counter() - t0
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({
    "mode": mode, "wall_s": wall, "loglik": float(res.final_loglik),
    "em_steps": steps,
    "rss_base_kb": int(base_kb), "rss_peak_kb": int(peak_kb),
    "rss_growth_kb": int(peak_kb - base_kb)}))
"""
    with tempfile.TemporaryDirectory() as root:
        path = os.path.join(root, "ingest-bench.bin")
        write_chunked(path)
        sides = {}
        for mode in ("resident", "pipelined", "minibatch"):
            env = dict(os.environ)
            if platform == "cpu":
                env["JAX_PLATFORMS"] = "cpu"
            r = subprocess.run(
                [sys.executable, "-c", child, path, mode,
                 str(k), str(block), str(iters)],
                capture_output=True, text=True, env=env)
            if r.returncode != 0:
                raise RuntimeError(
                    f"ingest bench child ({mode}) failed rc={r.returncode}:\n"
                    f"{r.stderr}")
            line = [ln for ln in r.stdout.splitlines()
                    if ln.startswith("{")][-1]
            sides[mode] = json.loads(line)

    res_side, pipe_side, mb_side = (sides["resident"], sides["pipelined"],
                                    sides["minibatch"])
    # The acceptance bit: bit-identical loglik, not a tolerance.
    parity = bool(res_side["loglik"] == pipe_side["loglik"])
    rss_ratio = (res_side["rss_growth_kb"]
                 / max(pipe_side["rss_growth_kb"], 1))
    mb_rel_err = (abs(mb_side["loglik"] - res_side["loglik"])
                  / max(abs(res_side["loglik"]), 1e-12))
    # The minibatch acceptance bound: health_regression_scale (10, the
    # GMMConfig default) x convergence_epsilon(n, d) (ops/formulas.py:
    # free-params-per-cluster x log(n*d) x 0.01), in absolute loglik units.
    # Scored as a REGRESSION (the health system's semantics): only a
    # minibatch endpoint WORSE than the full-EM endpoint counts against the
    # bound -- the gamma-sum step budget adds margin, so the stepwise run
    # routinely lands slightly past the equal-budget full-EM endpoint.
    fppc = 1.0 + d + 0.5 * d * (d + 1)
    mb_tol = 10.0 * fppc * np.log(float(n) * d) * 0.01
    mb_abs_err = abs(mb_side["loglik"] - res_side["loglik"])
    mb_regression = max(0.0, res_side["loglik"] - mb_side["loglik"])
    result = {
        "metric": f"pipelined ingest RSS-growth reduction "
                  f"({n}x{d}, K={k}, block={block}, {platform})",
        "value": round(rss_ratio, 3),
        "unit": "x",
        # resident / pipelined RSS growth (the memory headline), NOT the
        # NumPy baseline.
        "vs_baseline": round(rss_ratio, 3),
        "accelerator_unavailable": accel_unavailable,
        "ingest": {
            "n": n, "d": d, "k": k, "chunk_size": block,
            "em_iters": iters,
            "resident": res_side,
            "pipelined": pipe_side,
            "minibatch": mb_side,
            "loglik_parity": parity,
            "rss_growth_ratio": round(rss_ratio, 3),
            "wall_ratio": round(res_side["wall_s"]
                                / max(pipe_side["wall_s"], 1e-9), 3),
            "minibatch_rel_err": round(mb_rel_err, 8),
            "minibatch_abs_err": round(mb_abs_err, 6),
            "minibatch_regression": round(mb_regression, 6),
            "minibatch_tolerance": round(float(mb_tol), 6),
            "minibatch_within_tolerance": bool(mb_regression <= mb_tol),
            "minibatch_steps": int(mb_side["em_steps"]),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement of the ingestion path")
    return result


def run_elastic_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --elastic mode: warm elastic recovery vs cold restart A/B.

    Simulated single-process chaos (the same harness the elastic tests
    use): a generation-0 membership pre-seeded with ranks (0, 1) makes
    this process rank 0 of a 2-host world on paper, and an injected
    ``rank_lost`` mid-sweep declares rank 1 dead at a deterministic EM
    iteration. Three fits over the same blobs:

      reference  no fault -- ground-truth wall and selected model;
      cold       rank_lost with --elastic OFF -> PeerLostError (the
                 exit-75 operator path), then a from-scratch relaunch in
                 a fresh checkpoint dir: wall = partial run + full rerun;
      elastic    rank_lost with --elastic ON -> ONE call that shrinks to
                 generation 1, restores the emergency checkpoint, and
                 finishes the sweep: wall includes the whole recovery.

    ``vs_baseline`` is cold_total / elastic wall -- the time a fleet
    operator saves per peer loss by shrinking instead of relaunching.
    The record also carries the determinism checks the acceptance
    criteria name: same winner K as the reference and a final loglik
    within ``health_regression_scale x convergence_epsilon``. Size
    knobs: GMM_BENCH_ELASTIC_{N,D,K,ITERS}.
    """
    import tempfile

    import jax

    jax.config.update("jax_enable_x64", True)  # the fits run float64

    from cuda_gmm_mpi_tpu import supervisor
    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.parallel import elastic
    from cuda_gmm_mpi_tpu.testing import faults

    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("GMM_BENCH_ELASTIC_N")
            or (200_000 if on_accel else 40_000))
    d = int(os.environ.get("GMM_BENCH_ELASTIC_D") or 8)
    kmax = int(os.environ.get("GMM_BENCH_ELASTIC_K") or 6)
    iters = int(os.environ.get("GMM_BENCH_ELASTIC_ITERS") or 12)
    # Fire past the midpoint so the partial run is a meaningful fraction
    # of the reference wall (a loss at iteration 1 makes any restart
    # strategy look cheap).
    fault_iter = max(2, (2 * iters) // 3)

    rng = np.random.default_rng(7)
    centers = rng.normal(scale=8.0, size=(4, d))
    data = (centers[rng.integers(0, 4, n)]
            + rng.normal(size=(n, d))).astype(np.float64)

    def cfg(ck, **kw):
        base = dict(min_iters=iters, max_iters=iters, chunk_size=4096,
                    dtype="float64", checkpoint_dir=ck, seed=11,
                    preempt_poll_iters=1, elastic_backoff_s=0.1)
        base.update(kw)
        return GMMConfig(**base)

    def sup():
        return supervisor.RunSupervisor(install_signals=False)

    fault = {"rank_lost": {"iter": fault_iter, "rank": 1}}
    with tempfile.TemporaryDirectory() as root:
        # Reference: the uninterrupted wall and ground-truth model.
        elastic.reset()
        t0 = time.perf_counter()
        with supervisor.use(sup()):
            ref = fit_gmm(data, kmax, 2,
                          config=cfg(os.path.join(root, "ck_ref")))
        ref_wall = time.perf_counter() - t0

        # Cold side: loss -> exit-75 path -> from-scratch relaunch.
        elastic.reset()
        t0 = time.perf_counter()
        try:
            with faults.use(fault):
                with supervisor.use(sup()):
                    fit_gmm(data, kmax, 2,
                            config=cfg(os.path.join(root, "ck_cold")))
            raise RuntimeError("rank_lost injection never fired")
        except supervisor.PeerLostError:
            pass
        partial_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        with supervisor.use(sup()):
            cold = fit_gmm(data, kmax, 2,
                           config=cfg(os.path.join(root, "ck_cold2")))
        restart_wall = time.perf_counter() - t0
        cold_total = partial_wall + restart_wall

        # Elastic side: the same loss, survived in one call.
        elastic.reset()
        ck_el = os.path.join(root, "ck_el")
        elastic.write_membership(
            elastic.membership_dir(ck_el),
            elastic.Membership(generation=0, ranks=(0, 1), world_size0=2))
        t0 = time.perf_counter()
        with faults.use(fault):
            with supervisor.use(sup()):
                el = fit_gmm(data, kmax, 2,
                             config=cfg(ck_el, elastic=True, min_hosts=1))
        elastic_wall = time.perf_counter() - t0
        gen = elastic.generation()
        elastic.reset()

    speedup = cold_total / max(elastic_wall, 1e-9)
    # The acceptance tolerance: health_regression_scale (10, the GMMConfig
    # default) x convergence_epsilon(n, d) (ops/formulas.py), absolute
    # loglik units -- same bound the health monitor applies to a resume.
    fppc = 1.0 + d + 0.5 * d * (d + 1)
    tol = 10.0 * fppc * np.log(float(n) * d) * 0.01
    err = abs(float(el.final_loglik) - float(ref.final_loglik))
    result = {
        "metric": f"elastic recovery speedup vs cold restart "
                  f"({n}x{d}, K<= {kmax}, {platform})",
        "value": round(speedup, 3),
        "unit": "x",
        # cold-restart wall / elastic wall for the SAME injected loss.
        "vs_baseline": round(speedup, 3),
        "accelerator_unavailable": accel_unavailable,
        "elastic": {
            "n": n, "d": d, "k_max": kmax, "em_iters": iters,
            "fault_iter": fault_iter,
            "ref_wall_s": round(ref_wall, 3),
            "cold_partial_wall_s": round(partial_wall, 3),
            "cold_restart_wall_s": round(restart_wall, 3),
            "cold_total_wall_s": round(cold_total, 3),
            "elastic_wall_s": round(elastic_wall, 3),
            "recovery_overhead_s": round(elastic_wall - ref_wall, 3),
            "generation": int(gen),
            "winner_k_ref": int(ref.ideal_num_clusters),
            "winner_k_elastic": int(el.ideal_num_clusters),
            "winner_k_cold": int(cold.ideal_num_clusters),
            "winner_k_match": bool(int(el.ideal_num_clusters)
                                   == int(ref.ideal_num_clusters)),
            "loglik_abs_err": round(err, 9),
            "loglik_tolerance": round(float(tol), 6),
            "within_tolerance": bool(err <= tol),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed); this is a "
            "CPU-fallback measurement of the recovery path")
    return result


CONFIGS = {
    # BASELINE.md benchmark config matrix (1-5); "north" = the north-star;
    # 6 = the reference's first-class envelope (MAX_CLUSTERS=512,
    # NUM_DIMENSIONS=32 -- gaussian.h:10,16); "5stream" = config 5 run
    # out-of-core (--stream-events: chunks stay in host RAM, the scale
    # path for N past HBM -- its vs_baseline shows what streaming costs
    # against the same CPU denominator).
    "north": dict(n=1_000_000, d=24, k=100, diag=False),
    "1": dict(n=10_000, d=4, k=8, diag=False),
    "2": dict(n=100_000, d=21, k=64, diag=False),
    "3": dict(n=1_000_000, d=24, k=256, diag=True),
    "4": dict(n=500_000, d=16, k=100, diag=False, target_k=10),
    "5": dict(n=10_000_000, d=24, k=128, diag=False),
    "5stream": dict(n=10_000_000, d=24, k=128, diag=False, stream=True),
    "6": dict(n=1_000_000, d=32, k=512, diag=False),
}


def run_tune_bench(platform: str, accel_unavailable: bool) -> dict:
    """The --tune mode: autotuned-vs-hand-set-default A/B in ONE record.

    Probes the full chunk-size ladder into a throwaway tuning DB
    (``tuning.probe``, ``GMM_BENCH_TUNE_PROBE_ITERS`` EM iterations per
    candidate), resolves a config through ``autotune='db'``, then fits
    the same data at a fixed K twice -- once with the GMMConfig defaults
    (``autotune='off'``, chunk 65536: the hand-set geometry this PR
    replaces), once with the tuned knobs. Both sides warm their own
    model first so compile stays out of the timed walls.

    ``vs_baseline`` is the default/tuned wall ratio (>1 = the tuner
    won). The record carries every resolved decision (knob, chosen,
    source, candidate walls), the probe's own cost, and parity: knob
    sets that come out identical guarantee bit-equal logliks; a
    different chunk size is the documented reduction-order tolerance
    class (float32 rel ~1e-6; see docs/PERF.md "Autotuning") and the
    measured rel diff is recorded either way.

    Size knobs: GMM_BENCH_TUNE_N (default 200k accel / 20k CPU),
    GMM_BENCH_TUNE_D (16), GMM_BENCH_TUNE_K (8), GMM_BENCH_TUNE_ITERS
    (timed EM iterations, 5), GMM_BENCH_TUNE_PROBE_ITERS (2).
    """
    on_accel = platform not in ("cpu",)
    n = int(os.environ.get("GMM_BENCH_TUNE_N")
            or (200_000 if on_accel else 20_000))
    d = int(os.environ.get("GMM_BENCH_TUNE_D") or 16)
    k = int(os.environ.get("GMM_BENCH_TUNE_K") or 8)
    iters = int(os.environ.get("GMM_BENCH_TUNE_ITERS") or 5)
    probe_iters = int(os.environ.get("GMM_BENCH_TUNE_PROBE_ITERS") or 2)

    import dataclasses
    import tempfile

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel
    from cuda_gmm_mpi_tpu.models.order_search import fit_gmm
    from cuda_gmm_mpi_tpu.tuning import (TuningDB, probe_knob,
                                         resolve_fit_config_ex)
    from cuda_gmm_mpi_tpu.tuning.autotune import _platform_key

    rng = np.random.default_rng(7)
    centers = rng.normal(scale=8.0, size=(k, d))
    data = (
        centers[rng.integers(0, k, n)]
        + rng.normal(scale=1.0, size=(n, d))
    ).astype(np.float32)

    tmp = tempfile.mkdtemp(prefix="gmm_tune_bench_")
    dbp = os.path.join(tmp, "tuning.json")
    base = dict(min_iters=iters, max_iters=iters, seed=0)

    # Offline probe sweep (the `gmm tune` path), timed separately: the
    # tuner's own cost must never hide inside either A/B wall.
    cfg0 = GMMConfig(**base)
    key = _platform_key(cfg0, n, d, k)
    db = TuningDB.open(dbp)
    t0 = time.perf_counter()
    probe_knob(cfg0, data, k, key, db, "chunk_size", iters=probe_iters,
               full_ladder=True)
    db.save()
    probe_wall = time.perf_counter() - t0

    tuned_cfg, decisions = resolve_fit_config_ex(
        GMMConfig(autotune="db", tuning_db=dbp, **base), data, k)

    def one(cfg):
        model = GMMModel(cfg)
        warm = dataclasses.replace(cfg, min_iters=1, max_iters=1)
        fit_gmm(data, k, k, warm, model=model)
        t1 = time.perf_counter()
        res = fit_gmm(data, k, k, cfg, model=model)
        wall = time.perf_counter() - t1
        return {
            "wall_s": round(wall, 3),
            "chunk_size": int(cfg.chunk_size),
            "estep_backend": cfg.estep_backend,
            "final_loglik": float(res.final_loglik),
            "ideal_k": int(res.ideal_num_clusters),
        }

    default = one(cfg0)
    tuned = one(tuned_cfg)
    speedup = default["wall_s"] / max(tuned["wall_s"], 1e-9)
    bit_parity_expected = (
        tuned["chunk_size"] == default["chunk_size"]
        and tuned["estep_backend"] == default["estep_backend"])
    rel_ll = (abs(tuned["final_loglik"] - default["final_loglik"])
              / max(abs(default["final_loglik"]), 1e-30))
    parity_ok = ((rel_ll == 0.0) if bit_parity_expected
                 else rel_ll <= 1e-5)
    result = {
        "metric": f"autotuned vs default wall ({n}x{d}, K={k}, "
                  f"{platform})",
        "value": tuned["wall_s"],
        "unit": "s",
        # A/B ratio (default / tuned): > 1 means the tuner won.
        "vs_baseline": round(speedup, 3),
        "accelerator_unavailable": accel_unavailable,
        "tune": {
            "n": n, "d": d, "k": k, "em_iters": iters,
            "probe_iters": probe_iters,
            "probe_wall_s": round(probe_wall, 3),
            "tuning_key": key.as_str(),
            "decisions": [
                {"knob": dec["knob"], "chosen": dec["chosen"],
                 "source": dec["source"],
                 "default": dec.get("default"),
                 "candidates": dec.get("candidates") or {}}
                for dec in decisions],
            "default": default,
            "tuned": tuned,
            "speedup": round(speedup, 3),
            "bit_parity_expected": bit_parity_expected,
            "rel_loglik_diff": rel_ll,
            "parity_ok": parity_ok,
            "ideal_k_equal": tuned["ideal_k"] == default["ideal_k"],
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if accel_unavailable:
        result["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result")
    return result


def main() -> int:
    cfg_name = "north"
    for a in sys.argv[1:]:
        if a.startswith("--config="):
            cfg_name = a.split("=", 1)[1]
    want_sweep = ("--sweep" in sys.argv[1:]
                  or os.environ.get("GMM_BENCH_SWEEP") == "1")
    want_restarts = ("--restarts" in sys.argv[1:]
                     or bool(os.environ.get("GMM_BENCH_RESTARTS")))
    want_envelope = ("--envelope" in sys.argv[1:]
                     or os.environ.get("GMM_BENCH_ENVELOPE") == "1")
    want_serve = ("--serve" in sys.argv[1:]
                  or os.environ.get("GMM_BENCH_SERVE") == "1")
    want_http = ("--http" in sys.argv[1:]
                 or os.environ.get("GMM_BENCH_HTTP") == "1")
    want_drift = ("--drift" in sys.argv[1:]
                  or os.environ.get("GMM_BENCH_DRIFT") == "1")
    want_lifecycle = ("--lifecycle" in sys.argv[1:]
                      or os.environ.get("GMM_BENCH_LIFECYCLE") == "1")
    want_tenancy = ("--tenancy" in sys.argv[1:]
                    or os.environ.get("GMM_BENCH_TENANCY") == "1")
    want_ingest = ("--ingest" in sys.argv[1:]
                   or os.environ.get("GMM_BENCH_INGEST") == "1")
    want_elastic = ("--elastic" in sys.argv[1:]
                    or os.environ.get("GMM_BENCH_ELASTIC") == "1")
    want_obs = ("--obs" in sys.argv[1:]
                or os.environ.get("GMM_BENCH_OBS") == "1")
    want_profile = ("--profile" in sys.argv[1:]
                    or os.environ.get("GMM_BENCH_PROFILE") == "1")
    want_timeline = ("--timeline" in sys.argv[1:]
                     or os.environ.get("GMM_BENCH_TIMELINE") == "1")
    want_tune = ("--tune" in sys.argv[1:]
                 or os.environ.get("GMM_BENCH_TUNE") == "1")
    spec = CONFIGS.get(cfg_name)
    if spec is None:
        print(
            f"bench.py: unknown --config={cfg_name!r}; valid: "
            + ", ".join(sorted(CONFIGS)),
            file=sys.stderr,
        )
        return 2

    # NOTE: JAX_PLATFORMS env is NOT authoritative on this image (a
    # sitecustomize hook re-pins jax_platforms to the accelerator), so CPU
    # selection must go through config.update. GMM_BENCH_CPU=1 forces CPU
    # and skips the probe entirely (reliable escape hatch for CI).
    want_cpu = os.environ.get("GMM_BENCH_CPU") == "1"
    accel_unavailable = False
    if not want_cpu and not probe_default_platform():
        # Wedged/unavailable accelerator tunnel: fall back to CPU rather than
        # hanging the harness; the platform is recorded in the metric AND in
        # an explicit note so a CPU-fallback number is never mistaken for an
        # accelerator regression.
        if os.environ.get("GMM_BENCH_REQUIRE_ACCEL") == "1":
            print(json.dumps({
                "metric": f"EM iters/sec (config={cfg_name})",
                "value": 0.0,
                "unit": "iters/sec",
                "vs_baseline": 0.0,
                "accelerator_unavailable": True,
                "platform_note": (
                    "accelerator probe failed and GMM_BENCH_REQUIRE_ACCEL=1 "
                    "-- skipping the CPU fallback measurement"),
            }), flush=True)
            return 3
        print("bench.py: accelerator probe failed; using CPU", file=sys.stderr)
        want_cpu = accel_unavailable = True
    elif not want_cpu:
        settle_after_probe()

    # Watchdog: the probe only proves the accelerator was alive at start;
    # a tunnel that dies MID-RUN would hang the measurement forever and
    # leave the harness with no artifact at all. After the deadline, emit
    # an explicit unavailable-JSON and exit 3 (same contract as the probe
    # fallback, but distinguishable via "watchdog": true).
    import threading

    watchdog_s = float(os.environ.get("GMM_BENCH_WATCHDOG_S", 1800))

    def _watchdog_fire():
        print(json.dumps({
            "metric": f"EM iters/sec (config={cfg_name})",
            "value": 0.0,
            "unit": "iters/sec",
            "vs_baseline": 0.0,
            "accelerator_unavailable": True,
            "watchdog": True,
            "platform_note": (
                f"benchmark exceeded {watchdog_s:.0f}s after a successful "
                "accelerator probe -- the device likely died mid-run; no "
                "measurement was completed"),
        }), flush=True)
        os._exit(3)

    # Accelerator runs only: CPU runs (deliberate or probe-fallback) have
    # no tunnel to die mid-run, and the rc-0 CPU contract must hold even
    # on a slow host.
    watchdog = threading.Timer(watchdog_s, _watchdog_fire)
    watchdog.daemon = True
    if not want_cpu:
        watchdog.start()

    import jax

    if want_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    if want_sweep:
        # The headline-workload mode: bucketed-vs-off order-search A/B
        # (ignores --config's fixed-K shape; sized by GMM_BENCH_SWEEP_*).
        result = run_sweep_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_restarts:
        # Batched-vs-sequential n_init A/B (ignores --config; sized by
        # GMM_BENCH_RESTART_* / GMM_BENCH_RESTARTS).
        result = run_restart_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_envelope:
        # Fused-kernel-vs-jnp A/B on the K=512/D=32 reference envelope
        # (ignores --config; sized by GMM_BENCH_ENVELOPE_*).
        result = run_envelope_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_serve:
        # Serving cold-vs-warm A/B over the AOT executable cache
        # (ignores --config; sized by GMM_BENCH_SERVE_*).
        result = run_serve_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_http:
        # Network-tier contract: closed-loop TCP load against a real
        # `gmm serve --http --workers` subprocess tree, with a mid-load
        # worker SIGKILL (ignores --config; sized by GMM_BENCH_HTTP_*).
        result = run_http_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_drift:
        # Serve-time drift-detection contract: in-distribution vs
        # shifted traffic through the drift plane (ignores --config;
        # sized by GMM_BENCH_DRIFT_*).
        result = run_drift_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_lifecycle:
        # Closed-loop lifecycle contract: injected drift -> retrain ->
        # canary -> promote -> injected regression -> rollback (ignores
        # --config; sized by GMM_BENCH_LIFECYCLE_*).
        result = run_lifecycle_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_tenancy:
        # Batched-fleet-vs-sequential multi-tenant A/B (ignores
        # --config; sized by GMM_BENCH_TENANTS / GMM_BENCH_TENANCY_*).
        result = run_tenancy_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_ingest:
        # Host-resident vs pipelined out-of-core ingestion A/B (ignores
        # --config; sized by GMM_BENCH_INGEST_*).
        result = run_ingest_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_elastic:
        # Warm elastic recovery vs cold restart A/B after an injected
        # peer loss (ignores --config; sized by GMM_BENCH_ELASTIC_*).
        result = run_elastic_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_obs:
        # Telemetry-off vs stream vs live-plane overhead A/B/C (ignores
        # --config; sized by GMM_BENCH_OBS_*).
        result = run_obs_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_profile:
        # Compile-introspection profile shape + identical-runs-diff-clean
        # contract (ignores --config; sized by GMM_BENCH_PROFILE_*).
        result = run_profile_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_timeline:
        # Perfetto trace-export contract: live-plane fit -> build_timeline
        # -> validate oracle (ignores --config; sized by
        # GMM_BENCH_TIMELINE_*).
        result = run_timeline_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    if want_tune:
        # Autotuned-vs-default A/B: probe the chunk ladder into a scratch
        # tuning DB, resolve through autotune='db', fit both sides
        # (ignores --config; sized by GMM_BENCH_TUNE_*).
        result = run_tune_bench(platform, accel_unavailable)
        watchdog.cancel()
        print(json.dumps(result))
        return 3 if accel_unavailable else 0

    n_events, n_dims, k = spec["n"], spec["d"], spec["k"]
    target_k = int(spec.get("target_k", 0))
    if on_accel:
        bench_iters = 20
    else:
        # Scaled down on CPU so the harness stays fast. GMM_BENCH_MAX_N
        # shrinks further for smoke runs (hw_session.sh's HW_SMOKE
        # end-to-end rehearsal keeps the full producer->analyzer pipeline
        # under test without 100k-event CPU configs).
        max_n = int(os.environ.get("GMM_BENCH_MAX_N") or 100_000)
        if max_n < 1:
            print(f"bench.py: GMM_BENCH_MAX_N={max_n} must be >= 1",
                  file=sys.stderr)
            return 2
        n_events = min(n_events, max_n)
        bench_iters = 5
    # GMM_BENCH_CHUNK tunes the chunk size (hardware sessions probe 131072
    # vs larger tiles). The CPU default 4096 is the CPU-optimal tile from
    # the round-5 sweep on this image's single-core host (1024..100000,
    # precompute on: 4096 ~ 2.3-2.8 iters/s vs 1.8 at 16384 vs 1.9
    # unchunked -- L2/L3 locality of the [chunk, D^2] feature block
    # dominates). Empty-string-safe like GMM_BENCH_PRECISION; nonpositive
    # values fail loudly here rather than degenerating inside chunk_events.
    chunk = int(os.environ.get("GMM_BENCH_CHUNK")
                or (131072 if on_accel else 4096))
    if chunk < 1:
        print(f"bench.py: GMM_BENCH_CHUNK={chunk} must be >= 1",
              file=sys.stderr)
        return 2
    if target_k:
        # Model-order-search configs sweep K..target_k full EM runs; fewer
        # iterations per K keeps the bench bounded.
        bench_iters = 5 if on_accel else 2
    # Small configs: never pad beyond the dataset (padding would inflate the
    # accelerator's per-iteration work and deflate vs_baseline).
    chunk = min(chunk, n_events)

    from cuda_gmm_mpi_tpu.config import GMMConfig
    from cuda_gmm_mpi_tpu.models.gmm import GMMModel, chunk_events
    from cuda_gmm_mpi_tpu.ops.formulas import convergence_epsilon
    from cuda_gmm_mpi_tpu.ops.seeding import seed_clusters_host

    rng = np.random.default_rng(42)
    centers = rng.normal(scale=8.0, size=(k, n_dims))
    data = (
        centers[rng.integers(0, k, n_events)]
        + rng.normal(scale=1.0, size=(n_events, n_dims))
    ).astype(np.float32)

    diag = bool(spec.get("diag", False))
    state = seed_clusters_host(data, k)

    # Matmul precision: full-covariance configs run 'high' (bf16_3x) -- the
    # round-3 matched-precision study (docs/PERF.md) measured ~1.4-1.8x over
    # true fp32 ('highest') with final means inside reduction-order noise;
    # diagonal configs keep 'highest' (where 'high' is both slower AND less
    # accurate). GMM_BENCH_PRECISION overrides; loglik is recorded so the
    # accuracy of the benched configuration is auditable.
    precision = os.environ.get("GMM_BENCH_PRECISION") or (
        "highest" if diag else "high"
    )
    # GMM_BENCH_PRECOMPUTE A/Bs the feature hoist on the official bench
    # artifact (full-covariance in-memory configs only -- the flag's own
    # domain; see GMMConfig.precompute_features). Default: ON for CPU runs
    # -- the NumPy baseline precomputes its own [N, D^2] features outside
    # the timed region, so hoisting is the like-for-like comparison, and
    # the round-5 CPU sweep measured it worth ~1.15-1.3x there; OFF on the
    # accelerator until the hw-session A/B settles the routing decision.
    env_pre = os.environ.get("GMM_BENCH_PRECOMPUTE")
    want_pre = env_pre == "1" if env_pre not in (None, "") else not on_accel
    precompute = want_pre and not diag and not spec.get("stream")

    # Opt-in telemetry consumption: the timed sweep writes the JSONL
    # event stream and the per-K numbers are read back from it (the same
    # consumer contract `gmm report` uses) instead of the in-process
    # sweep_log.
    metrics_path = os.environ.get("GMM_BENCH_METRICS") or None

    def measure(use_pallas: str):
        """(iters, dt, ll, final_state, sweep_extra) for one measured run."""
        if target_k:
            # Model-order-search config: time the full Rissanen sweep
            # K..target_k (gaussian.cu:479-960) via the fused
            # whole-sweep-on-device program. First call compiles; the timed
            # call reuses the executable (same model => cached jit).
            from cuda_gmm_mpi_tpu.models.order_search import fit_gmm

            fit_cfg = GMMConfig(min_iters=bench_iters, max_iters=bench_iters,
                                chunk_size=chunk, diag_only=diag,
                                matmul_precision=precision,
                                use_pallas=use_pallas, fused_sweep=True,
                                precompute_features=precompute,
                                metrics_file=metrics_path)
            fit_model = GMMModel(fit_cfg)
            fit_gmm(data, k, target_k, fit_cfg, model=fit_model)  # warm
            t0 = time.perf_counter()
            res = fit_gmm(data, k, target_k, fit_cfg, model=fit_model)
            sweep_wall = time.perf_counter() - t0
            if metrics_path:
                # The recorder truncates per run, so the file holds exactly
                # the timed fit's stream.
                from cuda_gmm_mpi_tpu.telemetry import read_stream

                timed = [
                    (r["k"], r["loglik"], r["score"], r["iters"],
                     r["seconds"])
                    for r in read_stream(metrics_path)
                    if r.get("event") == "em_done"
                ]
            else:
                timed = res.sweep_log
            iters = sum(int(r[3]) for r in timed)
            dt = sweep_wall
            # Event-cluster work units for the CPU comparison. Counts REAL
            # events only: chunk padding inflates dt, but that padding is
            # this framework's own overhead, so it is charged to our runtime
            # rather than credited as work (keeps vs_baseline honest, if
            # conservative).
            extra = {
                "sweep_wall_s": round(sweep_wall, 3),
                "sweep_ks": len(timed),
                "work_units": sum(
                    int(r[3]) * n_events * int(r[0]) for r in timed),
                "ideal_k": res.ideal_num_clusters,
            }
            if metrics_path:
                extra["telemetry_source"] = "jsonl"
            # CPU baseline runs at the starting K's shapes
            return iters, dt, res.final_loglik, state, extra

        cfg = GMMConfig(min_iters=bench_iters, max_iters=bench_iters,
                        chunk_size=chunk, diag_only=diag,
                        matmul_precision=precision,
                        use_pallas=use_pallas,
                        stream_events=bool(spec.get("stream", False)),
                        precompute_features=precompute)
        chunks, wts = chunk_events(data, cfg.chunk_size)
        if cfg.stream_events:
            from cuda_gmm_mpi_tpu.models.streaming import StreamingGMMModel

            model = StreamingGMMModel(cfg)
            _, chunks, wts = model.prepare(state, chunks, wts)
        else:
            model = GMMModel(cfg)
            chunks, wts = jnp.asarray(chunks), jnp.asarray(wts)
        eps = convergence_epsilon(n_events, n_dims)

        # Warmup/compile on the SAME jit instance that gets timed (a separate
        # warm model would leave the timed call paying compilation / cache
        # lookup for its own closure -- ~100ms+ of non-iteration overhead).
        # min/max_iters are dynamic args, so 1 warm iteration compiles the
        # exact executable the timed reps reuse.
        s, ll, _ = model.run_em(state, chunks, wts, eps,
                                min_iters=1, max_iters=1)
        jax.block_until_ready(s)

        # Timed reps: each rep gets a slightly perturbed seed state so no
        # layer of the stack (jit, runtime, remote-TPU tunnel) can serve a
        # cached result for a repeated identical execution, and the float()
        # readback inside the timing region forces completion on the host.
        times = []
        for r in range(3):
            sr = state.replace(
                means=state.means * (1.0 + 1e-6 * (r + 1))
            )
            t0 = time.perf_counter()
            s, ll_dev, iters = model.run_em(sr, chunks, wts, eps)
            ll = float(ll_dev)
            times.append(time.perf_counter() - t0)
        dt = min(times)
        # Report the rep spread alongside the min: remote-tunnel sessions
        # vary by up to ~25% (docs/PERF.md), so a single best number
        # without its range over-claims.
        extra = {"rep_wall_s": [round(t, 4) for t in times]}
        return int(iters), dt, ll, s, extra

    # 'auto' is the XLA path everywhere since the round-3 precision study
    # (docs/PERF.md); no Pallas fallback needed.
    iters, dt, ll, s, sweep_extra = measure("auto")
    iters_per_sec = iters / dt

    # CPU baseline: identical iteration in NumPy/BLAS on a subsample, scaled
    # per-event (the covariance inversions are per-iteration constants and are
    # included as-is).
    n_sub = min(50_000, n_events)
    xs = data[:n_sub].astype(np.float32)
    # Like-for-like features: diag configs use x*x [N, D] and the diagonal
    # iteration; full configs use the flattened outer products [N, D^2].
    if diag:
        x2s = xs * xs
        cpu_iteration = numpy_em_iteration_diag
    else:
        x2s = (xs[:, :, None] * xs[:, None, :]).reshape(n_sub, -1)
        cpu_iteration = numpy_em_iteration
    p0 = baseline_params(s, k)
    cpu_iteration(xs, x2s, p0)  # warm caches
    # Direct configs: min-of-reps on BOTH sides (the accelerator loop above
    # also takes min), best-case vs best-case. Sweep (target_k) configs time
    # a single accelerator sweep, so their vs_baseline is conservative.
    cpu_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_iteration(xs, x2s, p0)
        cpu_times.append(time.perf_counter() - t0)
    t_cpu_sub = min(cpu_times)
    cpu_iters_per_sec = 1.0 / (t_cpu_sub * (n_events / n_sub))
    if target_k:
        # Scale the measured CPU per-(event*cluster) cost over the sweep's
        # actual work (K shrinks as clusters merge).
        unit_s = t_cpu_sub / (n_sub * k)
        vs_baseline = (sweep_extra["work_units"] * unit_s) / dt
    else:
        vs_baseline = iters_per_sec / cpu_iters_per_sec

    cov = "diagonal" if diag else "full"
    note = dict(sweep_extra)
    if spec.get("stream"):
        note["streamed"] = True
    if precompute:
        note["precompute_features"] = True
    if diag:
        note["baseline_note"] = "CPU baseline runs the diagonal iteration"
    if accel_unavailable:
        note["platform_note"] = (
            "accelerator tunnel unavailable (probe failed after retries); "
            "this is a CPU-fallback measurement, not an accelerator result"
        )
    if on_accel and cfg_name == "north":
        note["session_band_ms_per_iter"] = SESSION_BAND_MS_PER_ITER
    note["measured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    kdesc = f"K={k}->{target_k}" if target_k else f"K={k}"
    streamed = ", streamed" if spec.get("stream") else ""
    result = {
        "metric": f"EM iters/sec ({n_events}x{n_dims}, {kdesc}, "
                  f"{cov} covariance{streamed}, {platform})",
        "value": round(iters_per_sec, 3),
        "unit": "iters/sec",
        "vs_baseline": round(vs_baseline, 2),
        # Top-level, machine-readable: True means the accelerator tunnel was
        # down and this run is a CPU fallback -- a harness must never mistake
        # it for an accelerator perf number (round-3's BENCH artifact did
        # exactly that; see VERDICT.md r3 weak-#3).
        "accelerator_unavailable": accel_unavailable,
        "loglik": float(ll),
        "wall_s_per_iter": round(dt / iters, 4),
        "cpu_baseline_iters_per_sec": round(cpu_iters_per_sec, 4),
        "precision": precision,
        **note,
    }
    watchdog.cancel()
    print(json.dumps(result))
    # Distinguishable failure: rc 3 marks "no accelerator" (the JSON line is
    # still printed so the artifact explains itself). rc 0 = real measurement
    # on the intended platform. GMM_BENCH_CPU=1 deliberately benches CPU, so
    # it stays rc 0.
    return 3 if accel_unavailable else 0


if __name__ == "__main__":
    sys.exit(main())
